"""Runtime anti-entropy: audit and repair the scheduler's trust chain.

The scheduler rests on a chain of mirrors that is normally maintained
purely by events: the bus is truth, ``SchedulerCache`` mirrors the bus
(informer watches), the staged host arrays mirror the cache
(``ClusterDeltaTracker`` marks + ``lower_nodes_delta``), and the staged
device arrays mirror the host arrays (donated row scatters). Epoch
fencing protects every link against *races* — nothing protects them
against *bugs*: a missed tracker mark, a mutation that bypassed an
informer method, a stale assume, a drifted staged row. The reference
leans on informer resync and assume expiry for exactly this drift class
(SURVEY §2.1); graftcheck (docs/DESIGN.md §11) proves the lowering
paths equal at review time, but only a runtime check can prove the
*live state* equal.

:class:`StateAuditor` runs budgeted periodic sweeps over three trust
boundaries:

1. **cache ↔ bus** — re-derive the expected cache contents from bus
   truth (through the same ``transform_node`` the informer applies) and
   diff: missing/extra/stale nodes, pods, metrics, reservations, gangs,
   quotas, plus orphaned and expired-but-lingering assumes.
2. **accounting invariants** — per-node non-DaemonSet requests never
   exceed allocatable, no pod is simultaneously pending and assigned,
   reservation credit never exceeds the reserved capacity, gang records
   stay in legal states (waiting/bound disjoint, both subsets of the
   children).
3. **device ↔ host parity** — a bounded, round-robin sample of staged
   rows is freshly re-lowered from typed truth
   (:func:`state.cluster.lower_node_rows` — the same per-row helper
   registry as the production lowerings) and compared bit-for-bit
   against the staged host AND device arrays, at the staging
   generation's own time base so freshness flips can never read as
   drift. With ``probe_rows=r`` over ``n`` rows every row is provably
   probed within ``ceil(n/r)`` sweeps — the cursor is deterministic,
   never sampled.

Repairs escalate along a ladder and every rung is counted
(``scheduler_audit_*`` metrics) — never a silent pass: **targeted**
(re-apply the drifted object through the scheduler's own informer
methods, which mark the delta tracker), **cache-rebuild** (drift count
at or above ``rebuild_threshold``, or an invariant violation with no
targeted fix: drop and re-derive the whole cache from bus truth), and
**full-restage** (any parity mismatch:
``StagedStateCache.invalidate()`` — the next solve re-lowers and
re-stages the world from scratch, bit-identical by construction).

Sweeps are wired into ``run_loop`` (every ``--audit-interval-rounds``
rounds) plus a mandatory **promotion sweep** when a standby acquires
the lease (``on_started_leading`` → :meth:`note_promotion`): a newly
promoted leader audits whatever the deposed leader left behind BEFORE
its first solve. ``status()`` rides the debug mux next to the
failover/supervisor status.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Tuple

import jax
import numpy as np

from koordinator_tpu.apis.types import (
    resources_to_vector,
    vector_to_resources,
)
from koordinator_tpu.client.bus import Kind
from koordinator_tpu.metrics.components import (
    AUDIT_DETECTIONS,
    AUDIT_LAST_DRIFT,
    AUDIT_PROBE_ROWS,
    AUDIT_REPAIRS,
    AUDIT_SWEEP_DURATION,
    AUDIT_SWEEPS,
    AUDIT_UNREPAIRED,
)
from koordinator_tpu.ops.binpack import STAGED_NODE_FIELDS
from koordinator_tpu.state.cluster import lower_node_rows

#: one detected drift: (kind, detail, repair closure or None)
Drift = Tuple[str, str, Optional[Callable[[], None]]]


class StateAuditor:
    """Budgeted anti-entropy sweeps + the counted repair ladder.

    ``scheduler`` is a wired :class:`~koordinator_tpu.scheduler.
    Scheduler`; ``bus`` the :class:`~koordinator_tpu.client.bus.
    APIServer` it is wired to (``None`` skips the cache↔bus boundary —
    standalone models still get invariants + the parity probe).

    Concurrency: sweeps run on the scheduling-loop thread between
    rounds; ``status()`` is read from debug-mux handler threads. Every
    mutable attribute below is mapped to ``_lock`` in graftcheck's
    lock-discipline registry.
    """

    def __init__(self, scheduler, bus=None, *, interval_rounds: int = 16,
                 probe_rows: int = 64, rebuild_threshold: int = 8,
                 assume_ttl_s: float = 900.0, warm_pool=None):
        self.scheduler = scheduler
        self.bus = bus
        self.interval_rounds = int(interval_rounds)
        self.probe_rows = int(probe_rows)
        self.rebuild_threshold = int(rebuild_threshold)
        self.assume_ttl_s = float(assume_ttl_s)
        #: AOT warm pool (service/warmpool.WarmPool, docs/DESIGN.md
        #: §21): the promotion sweep restores its executables and the
        #: staged world BEFORE the new leader's first solve, so a
        #: failover never pays a cold XLA compile. Set-once wiring
        #: (cmd/scheduler.py main), deliberately outside the lock map.
        self.warm_pool = warm_pool
        self._lock = threading.RLock()
        self._promotion_pending = False
        self._rounds_since = 0
        self._probe_cursor = 0
        #: fixerless invariant violations that persisted THROUGH a cache
        #: rebuild (bus truth itself broken): suppresses re-escalation
        #: while they last, re-armed the moment they heal
        self._unrepairable: set = set()
        self.sweeps: Dict[str, int] = {}
        self.detections: Dict[str, int] = {}
        self.repairs: Dict[str, int] = {}
        self.last_report: Optional[dict] = None

    # -- loop hooks ----------------------------------------------------------

    def note_promotion(self) -> None:
        """This instance just acquired the lease (wire to the elector's
        ``on_started_leading``): the next :meth:`on_round` runs a
        mandatory promotion sweep — exactly one per acquisition."""
        with self._lock:
            self._promotion_pending = True

    def sweep_due(self) -> bool:
        """Whether the NEXT :meth:`on_round` will run a sweep — consumed
        by the pipelined tick loop to quiesce (drain) the pipeline
        before a sweep ever reads the caches: an unretired tick's
        assumed-but-unpublished decisions would read as drift. Pure
        peek, consumes nothing."""
        with self._lock:
            if self._promotion_pending:
                return True
            return bool(
                self.interval_rounds
                and self._rounds_since + 1 >= self.interval_rounds
            )

    def on_round(self, now: Optional[float] = None) -> Optional[dict]:
        """One scheduling round is about to run. Runs the promotion
        sweep if one is pending (once per acquisition, not per round),
        else a periodic sweep every ``interval_rounds`` rounds. Returns
        the sweep report, or None when no sweep ran."""
        with self._lock:
            if self._promotion_pending:
                self._promotion_pending = False
                self._rounds_since = 0
                kind = "promotion"
            else:
                self._rounds_since += 1
                if self.interval_rounds and \
                        self._rounds_since >= self.interval_rounds:
                    self._rounds_since = 0
                    kind = "periodic"
                else:
                    return None
        # outside the lock (sweep re-acquires it for its own body): a
        # detection's flight dump does file I/O, and holding the RLock
        # across it would block status() readers and the pipelined
        # loop's sweep_due() quiesce check behind the disk
        report = self.sweep(kind, now=now)
        if kind == "promotion":
            # warm restart (docs/DESIGN.md §21): AFTER the sweep's
            # repairs (so the restored staged world reflects repaired
            # truth, not the deposed leader's leavings), restore the
            # warm pool's executables and eagerly re-stage the world —
            # the new leader's first solve then skips trace + compile
            # + full staging. Loads only: a corrupt store degrades the
            # first solve to cold compile, it never blocks promotion.
            # The published last_report is REPLACED, never mutated: a
            # debug-mux reader serializing the sweep's dict must not
            # see a key inserted mid-iteration.
            warm = self._warm_restore(now=now)
            report = dict(report)
            report["warm"] = warm
            with self._lock:
                self.last_report = report
        return report

    def _warm_restore(self, now: Optional[float] = None) -> Optional[dict]:
        """The promotion path's warm restore: pool executables from
        disk (typed failures quarantine + count and leave that shape
        cold) plus an eager staged-world prestage. Never raises — a
        failed warm restore costs latency, never the round."""
        out: dict = {}
        if self.warm_pool is not None:
            try:
                out["pool"] = self.warm_pool.restore(compile_missing=False)
            except Exception as e:  # pragma: no cover - defensive
                out["pool"] = {"error": f"{type(e).__name__}: {e}"}
        model = getattr(self.scheduler, "model", None)
        cache = getattr(self.scheduler, "cache", None)
        if model is not None and cache is not None and \
                hasattr(model, "prestage"):
            try:
                t0 = time.perf_counter()
                times = model.prestage(cache.snapshot(now=now))
                out["prestage"] = {
                    "wall_s": time.perf_counter() - t0,
                    "times": times,
                }
            except Exception as e:
                out["prestage"] = {"error": f"{type(e).__name__}: {e}"}
        return out or None

    # -- the sweep -----------------------------------------------------------

    def sweep(self, kind: str = "manual", now: Optional[float] = None) -> dict:
        """One full pass over the three trust boundaries; detections
        and repairs are applied, counted, and returned as a report."""
        with self._lock:
            t0 = time.perf_counter()
            at = now if now is not None else time.time()
            report: dict = {
                "kind": kind, "at": at, "detections": {}, "repairs": {},
                "unrepaired": [], "probe_rows": [], "probe_skipped": 0,
            }

            def detect(boundary: str, dkind: str, detail: str) -> None:
                AUDIT_DETECTIONS.inc({"boundary": boundary, "kind": dkind})
                key = f"{boundary}/{dkind}"
                report["detections"][key] = (
                    report["detections"].get(key, 0) + 1
                )

            def repaired(action: str) -> None:
                AUDIT_REPAIRS.inc({"action": action})
                report["repairs"][action] = (
                    report["repairs"].get(action, 0) + 1
                )

            # 1. cache <-> bus
            rebuilt = False
            if self.bus is not None:
                drifts = self._diff_cache_bus(at)
                for dkind, detail, _fix in drifts:
                    detect("cache-bus", dkind, detail)
                if drifts:
                    if len(drifts) >= self.rebuild_threshold:
                        self._rebuild_from_bus()
                        rebuilt = True
                        repaired("cache-rebuild")
                    else:
                        for _dkind, _detail, fix in drifts:
                            if fix is not None:
                                fix()
                                repaired("targeted")

            # 2. accounting invariants (on the post-repair cache)
            viols = self._check_invariants()
            for vkind, detail, _fix in viols:
                detect("accounting", vkind, detail)
            fixerless = {
                (vkind, detail)
                for vkind, detail, fix in viols if fix is None
            }
            # escalation memory: violations a previous rebuild provably
            # could not repair (bus truth itself broken) must not drive
            # a full O(cluster) rebuild — and a Permit-hold reset — on
            # EVERY sweep while they persist; healed entries re-arm
            self._unrepairable &= fixerless
            if (
                fixerless - self._unrepairable
                and self.bus is not None
                and not rebuilt
            ):
                self._rebuild_from_bus()
                rebuilt = True
                repaired("cache-rebuild")
                # the rebuild invalidated every captured fix closure:
                # re-derive against the rebuilt cache before repairing
                viols = self._check_invariants()
            if rebuilt and fixerless:
                # anything fixerless that survived THIS sweep's rebuild
                # is provably rebuild-proof — arm the memory whichever
                # boundary triggered the rebuild (viols is post-rebuild
                # either way: phase 1 rebuilds run before the check,
                # the branch above re-derives)
                self._unrepairable |= fixerless & {
                    (vkind, detail)
                    for vkind, detail, fix in viols if fix is None
                }
            for _vkind, _detail, fix in viols:
                if fix is not None:
                    fix()
                    repaired("targeted")
            if viols:
                # re-verify: anything that survived the ladder is
                # reported loudly, never silently dropped
                report["unrepaired"] = [
                    f"{vkind}:{detail}"
                    for vkind, detail, _ in self._check_invariants()
                ]

            # 3. device <-> host parity probe
            probe, self._probe_cursor = self._parity_probe(
                self._probe_cursor
            )
            report["probe_rows"] = probe["rows"]
            report["probe_skipped"] = probe["skipped"]
            AUDIT_PROBE_ROWS.inc(amount=len(probe["rows"]))
            if probe["mismatches"]:
                for name, what in probe["mismatches"]:
                    if what == "structure":
                        dkind = "staged-structure-drift"
                    elif what.startswith("host:"):
                        dkind = "staged-host-drift"
                    else:
                        dkind = "staged-device-drift"
                    detect("device-parity", dkind, f"{name}:{what}")
                # the heaviest rung: forget the staged world, next
                # solve re-lowers + re-stages from scratch
                self.scheduler.model.staged_cache.invalidate()
                repaired("full-restage")

            total = sum(report["detections"].values())
            AUDIT_LAST_DRIFT.set(total)
            AUDIT_UNREPAIRED.set(len(report["unrepaired"]))
            AUDIT_SWEEPS.inc({"kind": kind})
            self.sweeps[kind] = self.sweeps.get(kind, 0) + 1
            for key, n in report["detections"].items():
                self.detections[key] = self.detections.get(key, 0) + n
            for action, n in report["repairs"].items():
                self.repairs[action] = self.repairs.get(action, 0) + n
            report["duration_s"] = time.perf_counter() - t0
            AUDIT_SWEEP_DURATION.observe(report["duration_s"])
            self.last_report = report
        if total:
            # anomaly: drift was detected — dump the flight recorder's
            # recent rounds before the repaired state overwrites the
            # evidence (outside the lock: the dump does file I/O)
            from koordinator_tpu.obs.flight import FLIGHT
            from koordinator_tpu.obs.trace import TRACER

            TRACER.instant("auditor-detection", cat="audit",
                           args={"detections": total, "kind": kind})
            FLIGHT.trigger(
                "auditor-detection",
                detail=f"{total} detection(s) in {kind} sweep",
                extra={"detections": report["detections"],
                       "repairs": report["repairs"],
                       "unrepaired": report["unrepaired"]},
            )
        return report

    def status(self) -> dict:
        """Debug-mux payload (registered as ``state-auditor`` beside
        the failover/supervisor services)."""
        with self._lock:
            return {
                "interval_rounds": self.interval_rounds,
                "probe_rows": self.probe_rows,
                "rebuild_threshold": self.rebuild_threshold,
                "sweeps": dict(self.sweeps),
                "detections": dict(self.detections),
                "repairs": dict(self.repairs),
                "unrepairable": sorted(
                    f"{k}:{d}" for k, d in self._unrepairable
                ),
                "last": self.last_report,
            }

    # -- boundary 1: cache <-> bus -------------------------------------------

    def _diff_cache_bus(self, now: float) -> List[Drift]:
        """Expected cache contents from bus truth vs the live cache.
        Repair closures route through the scheduler's own informer
        methods so every fix marks the delta tracker and re-runs the
        accounting side effects the original event would have."""
        from koordinator_tpu.client.wiring import transform_node

        sched = self.scheduler
        cache = sched.cache
        drifts: List[Drift] = []

        # nodes (through the informer-level transform, or the trimmed
        # allocatable would read as drift every sweep)
        expected_nodes = {
            name: transform_node(node)
            for name, node in self.bus.list(Kind.NODE).items()
        }
        for name, want in expected_nodes.items():
            have = cache.nodes.get(name)
            if have is None:
                drifts.append(("missing-node", name,
                               lambda w=want: sched.add_node(w)))
            elif have != want:
                drifts.append(("stale-node", name,
                               lambda w=want: sched.add_node(w)))
        for name in list(cache.nodes):
            if name not in expected_nodes:
                drifts.append(("extra-node", name,
                               lambda n=name: sched.remove_node(n)))

        # node metrics
        bus_metrics = self.bus.list(Kind.NODE_METRIC)
        for name, want in bus_metrics.items():
            have = cache.node_metrics.get(name)
            if have is None:
                drifts.append(("missing-metric", name,
                               lambda w=want: sched.update_node_metric(w)))
            elif have != want:
                drifts.append(("stale-metric", name,
                               lambda w=want: sched.update_node_metric(w)))
        for name in list(cache.node_metrics):
            if name not in bus_metrics:
                drifts.append(("extra-metric", name,
                               lambda n=name: self._drop_metric(n)))

        # pods: placement truth is the load-bearing field
        bus_pods = {p.uid: p for p in self.bus.list(Kind.POD).values()}
        for uid, want in bus_pods.items():
            in_pods = cache.pods.get(uid)
            in_pending = cache.pending.get(uid)
            if want.node_name is not None and \
                    getattr(want, "waiting_permit", False):
                # an UNPUBLISHED Permit hold. Ours (tracked in _waiting)
                # is live local state, not drift. Anyone else's holder
                # is gone — a deposed leader's gang assume that never
                # published: adopting it as assigned would strand it
                # (no holds, never re-solved, capacity leaked), so
                # release it back to pending instead.
                if uid not in sched._waiting:
                    drifts.append(("orphan-permit-hold", uid,
                                   lambda u=uid:
                                   self._forget_permit_hold(u)))
                continue
            if in_pods is None and in_pending is None:
                drifts.append(("missing-pod", uid,
                               lambda w=want: sched.update_pod(w)))
                continue
            if want.node_name is not None:
                if in_pods is None or in_pods.node_name != want.node_name:
                    have = in_pods if in_pods is not None else in_pending
                    drifts.append(("stale-pod", uid,
                                   lambda h=have, w=want:
                                   self._readd_pod(h, w)))
            elif in_pods is not None:
                # the cache believes a bind the bus has no record of
                drifts.append(("stale-pod", uid,
                               lambda h=in_pods, w=want:
                               self._readd_pod(h, w)))
        for uid, have in (
            list(cache.pods.items()) + list(cache.pending.items())
        ):
            if uid not in bus_pods:
                drifts.append(("extra-pod", uid,
                               lambda h=have: sched.remove_pod(h)))

        # reservations
        bus_resv = self.bus.list(Kind.RESERVATION)
        for name, want in bus_resv.items():
            have = cache.reservations.get(name)
            if have is None:
                drifts.append(("missing-reservation", name,
                               lambda w=want: sched.update_reservation(w)))
            elif have != want:
                drifts.append(("stale-reservation", name,
                               lambda w=want: sched.update_reservation(w)))
        for name in list(cache.reservations):
            if name not in bus_resv:
                drifts.append(("extra-reservation", name,
                               lambda n=name: self._drop_reservation(n)))

        # gangs + quotas (no tracker marks — never in the node arrays)
        bus_gangs = self.bus.list(Kind.GANG)
        for name, want in bus_gangs.items():
            have = cache.gangs.get(name)
            if have is None or have != want:
                dkind = "missing-gang" if have is None else "stale-gang"
                drifts.append((dkind, name,
                               lambda w=want: sched.update_gang(w)))
        for name in list(cache.gangs):
            if name not in bus_gangs:
                drifts.append(("extra-gang", name,
                               lambda n=name: sched.remove_gang(n)))
        bus_quotas = self.bus.list(Kind.QUOTA)
        for name, want in bus_quotas.items():
            have = cache.quotas.get(name)
            if have is None or have != want:
                dkind = "missing-quota" if have is None else "stale-quota"
                drifts.append((dkind, name,
                               lambda w=want: sched.update_quota(w)))
        for name in list(cache.quotas):
            if name not in bus_quotas:
                drifts.append(("extra-quota", name,
                               lambda n=name: sched.remove_quota(n)))

        # assumes: orphaned entries and expired-but-lingering confirms
        for uid, at in list(cache.assumed.items()):
            pod = cache.pods.get(uid)
            if pod is None:
                drifts.append(("orphan-assume", uid,
                               lambda u=uid: cache.forget_pod(u)))
            elif (now - at) >= self.assume_ttl_s and \
                    not getattr(pod, "waiting_permit", False):
                want = bus_pods.get(uid)
                if want is not None and want.node_name == pod.node_name:
                    # the bind is bus-confirmed but the assume never
                    # finished — confirm it now instead of holding the
                    # "assumed" state forever
                    drifts.append(("lingering-assume", uid,
                                   lambda u=uid: cache.finish_binding(u)))
        return drifts

    def _forget_permit_hold(self, uid: str) -> None:
        """Release an orphaned Permit hold — an unpublished gang assume
        whose holder is gone (a deposed leader). The shared pod object
        returns to pending (with a tracker mark for the held node); the
        next round re-places the gang with full holds. No local
        accounting exists to release: this instance never held it."""
        sched = self.scheduler
        cache = sched.cache
        pod = cache.pods.get(uid)
        if pod is not None:
            cache.forget_pod(uid)  # resets node/waiting_permit + marks
        else:
            pod = cache.pending.get(uid)
            if pod is None:
                # not in the cache at all: reset the bus object, then
                # intake it as an ordinary pending pod
                bus_pod = None
                for p in self.bus.list(Kind.POD).values():
                    if p.uid == uid:
                        bus_pod = p
                        break
                if bus_pod is None:
                    return
                cache.delta_tracker.mark_node(bus_pod.node_name)
                bus_pod.node_name = None
                bus_pod.waiting_permit = False
                sched.update_pod(bus_pod)
            elif pod.node_name is not None:
                cache.delta_tracker.mark_node(pod.node_name)
                pod.node_name = None
                pod.waiting_permit = False
        sched.gang_manager.on_pod_forgotten(uid)

    def _readd_pod(self, have, want) -> None:
        """Stale placement: release the cached copy's holds through the
        full remove path, then re-enter the bus object as the informer
        would. (``update_pod`` alone would preserve the stale cached
        placement — its refresh path trusts the cache's node.)"""
        self.scheduler.remove_pod(have)
        self.scheduler.update_pod(want)

    def _drop_metric(self, name: str) -> None:
        self.scheduler.remove_node_metric(name)
        self.scheduler.cache.delta_tracker.mark_node(name)

    def _drop_reservation(self, name: str) -> None:
        resv = self.scheduler.cache.reservations.get(name)
        self.scheduler.remove_reservation(name)
        if resv is not None:
            self.scheduler.cache.delta_tracker.mark_node(resv.node_name)

    def _rebuild_from_bus(self) -> None:
        """The middle rung: drop the whole cache and re-derive it from
        bus truth through the same informer methods a fresh standby
        would use. Node add/removes mark the tracker's structure epoch,
        so the next solve full-relowers — the staged state heals with
        the cache.

        Permit-held (waiting) pods are RELEASED first, back to pending:
        their holds (quota used, fine-grained NUMA/device allocations,
        reservation credit) are local, unpublished state that cannot be
        reconstructed from bus truth — a half-restore would leak the
        quota accounting and double-allocate the released cpusets. The
        gang re-solves with full holds next round; a rebuild is a
        leadership-grade event and restarting the wait is the safe
        price."""
        from koordinator_tpu.client.wiring import transform_node

        sched = self.scheduler
        cache = sched.cache
        for uid in list(sched._waiting):
            sched._release_waiting(uid)
            sched.gang_manager.on_pod_forgotten(uid)
        for pod in list(cache.pods.values()) + list(cache.pending.values()):
            sched.remove_pod(pod)
        for uid in list(cache.assumed):
            cache.forget_pod(uid)  # orphans: pods were all removed
        for name in list(cache.node_metrics):
            sched.remove_node_metric(name)
        for name in list(cache.reservations):
            sched.remove_reservation(name)
        for name in list(cache.gangs):
            sched.remove_gang(name)
        for name in list(cache.quotas):
            sched.remove_quota(name)
        for name in list(cache.nodes):
            sched.remove_node(name)
        for node in self.bus.list(Kind.NODE).values():
            sched.add_node(transform_node(node))
        for metric in self.bus.list(Kind.NODE_METRIC).values():
            sched.update_node_metric(metric)
        for name, topo in self.bus.list(
            Kind.NODE_RESOURCE_TOPOLOGY
        ).items():
            sched.update_node_topology(name, topo)
        for name, entries in self.bus.list(Kind.DEVICE).items():
            sched.update_node_devices(name, entries)
        for quota in self.bus.list(Kind.QUOTA).values():
            sched.update_quota(quota)
        for gang in self.bus.list(Kind.GANG).values():
            sched.update_gang(gang)
        for resv in self.bus.list(Kind.RESERVATION).values():
            sched.update_reservation(resv)
        for pod in self.bus.list(Kind.POD).values():
            sched.update_pod(pod)
        # post-rebuild, every remaining Permit hold is orphaned (our own
        # were released before the teardown): release, don't adopt
        for uid, pod in list(cache.pods.items()):
            if getattr(pod, "waiting_permit", False):
                self._forget_permit_hold(uid)

    # -- boundary 2: accounting invariants -----------------------------------

    def _check_invariants(self) -> List[Drift]:
        sched = self.scheduler
        cache = sched.cache
        viols: List[Drift] = []

        # no pod simultaneously pending and assigned
        for uid in sorted(set(cache.pods) & set(cache.pending)):
            def fix_double(u=uid):
                if self.bus is not None:
                    bus_pods = {
                        p.uid: p
                        for p in self.bus.list(Kind.POD).values()
                    }
                    have = cache.pods.get(u)
                    if have is not None:
                        sched.remove_pod(have)
                    want = bus_pods.get(u)
                    if want is not None:
                        sched.update_pod(want)
                else:
                    cache.pending.pop(u, None)  # the assigned copy wins
            viols.append(("double-placed", uid, fix_double))

        # per-node used <= allocatable (non-DaemonSet requests only:
        # DaemonSets bypass Fit by design)
        used: Dict[str, np.ndarray] = {}
        for pod in list(cache.pods.values()):
            if pod.node_name is None or pod.is_daemonset:
                continue
            vec = resources_to_vector(pod.requests)
            cur = used.get(pod.node_name)
            used[pod.node_name] = vec if cur is None else cur + vec
        for name in sorted(used):
            node = cache.nodes.get(name)
            if node is None:
                continue  # extra-pod/extra-node drift owns this case
            alloc = resources_to_vector(node.allocatable)
            if bool(np.any(used[name] > alloc)):
                # no targeted fix exists: which pod is the liar is
                # unknowable locally — escalate to a bus rebuild
                viols.append(("node-overcommit", name, None))

        # reservation credit <= reserved capacity
        for name in sorted(cache.reservations):
            resv = cache.reservations[name]
            cap = resources_to_vector(resv.allocatable or resv.requests)
            got = resources_to_vector(resv.allocated)
            if bool(np.any(got > cap)):
                def fix_resv(r=resv, c=cap, g=got):
                    r.allocated = vector_to_resources(np.minimum(g, c))
                    cache.delta_tracker.mark_node(r.node_name)
                viols.append(("resv-overcredit", name, fix_resv))

        # gang records in legal states
        for name in sorted(sched.gang_manager.gangs):
            record = sched.gang_manager.gangs[name]
            overlap = record.waiting & record.bound
            strays = (record.waiting | record.bound) - record.children
            if overlap or strays:
                def fix_gang(rec=record):
                    rec.waiting -= rec.bound  # bound wins the overlap
                    rec.waiting &= rec.children
                    rec.bound &= rec.children
                viols.append(("gang-illegal-state", name, fix_gang))
        return viols

    # -- boundary 3: device <-> host parity probe ------------------------

    def _parity_probe(self, cursor: int) -> Tuple[dict, int]:
        """Re-lower ``probe_rows`` staged rows from typed truth and
        compare bit-for-bit against the staged host and device arrays.
        Rows are taken round-robin from ``cursor`` — deterministic
        coverage of every row within ``ceil(n/probe_rows)`` sweeps.
        Rows dirty since the staged generation are skipped (they are
        LEGITIMATELY stale until the next solve re-lowers them)."""
        out: dict = {"rows": [], "skipped": 0, "mismatches": []}
        model = getattr(self.scheduler, "model", None)
        staged = getattr(model, "staged_cache", None)
        if staged is None or not self.probe_rows:
            return out, cursor
        arrays, state, tracker, seen_epoch, last_now = staged.audit_view()
        if arrays is None or tracker is None or last_now is None:
            return out, cursor  # nothing staged yet
        if tracker.structure_epoch > seen_epoch:
            return out, cursor  # full relower already pending
        names = arrays.names
        n = len(names)
        if n == 0:
            return out, cursor
        take = min(self.probe_rows, n)
        # the probe's truth is lowered at the staged generation's OWN
        # time base, so metric-freshness flips between solves can never
        # read as drift. Snapshot BEFORE reading the dirty set: a bus
        # update landing between the two then shows up as dirty and is
        # skipped (safe); the reverse order would compare new truth
        # against old staging and cry drift on a healthy row.
        snapshot = self.scheduler.cache.snapshot(now=last_now)
        dirty = set(tracker.dirty_since(seen_epoch))
        snap_names = {node.name for node in snapshot.nodes}
        probe_idx = [(cursor + i) % n for i in range(take)]
        cursor = (cursor + take) % n
        #: (position in probe_idx, row index, name) of comparable rows —
        #: dirty rows are LEGITIMATELY stale until the next solve, so
        #: they are read back (constant gather shape) but not compared
        comparable: List[Tuple[int, int, str]] = []
        for pos, j in enumerate(probe_idx):
            name = names[j]
            if name in dirty:
                out["skipped"] += 1
                continue
            if name not in snap_names:
                # the node set changed without a structure mark: the
                # staged world's very shape is drifted
                out["mismatches"].append((name, "structure"))
                continue
            comparable.append((pos, j, name))
        if not comparable:
            return out, cursor
        probe_names = [name for _, _, name in comparable]
        out["rows"] = probe_names
        truth = lower_node_rows(
            snapshot, probe_names, **model.lowering_kwargs()
        )
        dev = None
        if state is not None:
            # the ONE intentional device->host sync point in the control
            # plane: a bounded read-back of the sampled staged rows,
            # between rounds, never on the solve path (allowlisted in
            # graftcheck.toml with this justification). The gather is
            # always the full ``take`` rows — a constant shape per
            # (n, probe_rows), so XLA compiles it exactly once instead
            # of once per distinct dirty-row count.
            sel = np.asarray(probe_idx, dtype=np.int32)
            dev = jax.device_get(
                {f: getattr(state, f)[sel] for f in STAGED_NODE_FIELDS}
            )
        host_sel = np.asarray([j for _, j, _ in comparable], dtype=np.int64)
        dev_sel = np.asarray(
            [pos for pos, _, _ in comparable], dtype=np.int64
        )
        # block compare per field; drill down per row only on mismatch
        # (the healthy-sweep fast path is 2 compares per field)
        for f in STAGED_NODE_FIELDS:
            want = truth[f]
            host = getattr(arrays, f)[host_sel]
            if not np.array_equal(host, want):
                for k, (_pos, _j, name) in enumerate(comparable):
                    if not np.array_equal(host[k], want[k]):
                        out["mismatches"].append((name, f"host:{f}"))
            if dev is not None:
                dev_block = dev[f][dev_sel]
                if not np.array_equal(dev_block, want):
                    for k, (_pos, _j, name) in enumerate(comparable):
                        if not np.array_equal(dev_block[k], want[k]):
                            out["mismatches"].append(
                                (name, f"device:{f}")
                            )
        return out, cursor
