"""Extension-point protocol and the incremental scheduling cycle.

Mirrors the reference's framework-extender architecture
(pkg/scheduler/frameworkext/framework_extender.go:167-262 overrides of
RunPreFilterPlugins / RunFilterPluginsWithNominatedPods / RunScorePlugins /
RunPreBindPlugins, and the transformer extension points in interface.go:
78-97): plugins see typed snapshots and may rewrite the pod/node view
before each phase. The per-pod cycle here is the semantics oracle for the
batched solver and the path for one-off scheduling (tiny clusters, tests,
debug dumps); bulk scheduling goes through models/placement.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

from koordinator_tpu.apis.types import ClusterSnapshot, NodeSpec, PodSpec

MAX_NODE_SCORE = 100


class CycleState(dict):
    """Per-scheduling-cycle scratch space shared between plugins
    (reference: framework.CycleState)."""


class Status:
    """Plugin status: success (None reason) or failure with a reason."""

    def __init__(self, reason: Optional[str] = None, unschedulable: bool = False):
        self.reason = reason
        self.unschedulable = unschedulable

    @property
    def ok(self) -> bool:
        return self.reason is None

    @classmethod
    def success(cls) -> "Status":
        return cls()

    @classmethod
    def unschedulable_(cls, reason: str) -> "Status":
        return cls(reason=reason, unschedulable=True)

    def __repr__(self) -> str:
        return f"Status(ok={self.ok}, reason={self.reason!r})"


class Plugin:
    """Base plugin. Override any subset of the extension points.

    Extension points (in cycle order), mirroring the k8s framework plus
    the koordinator transformers:

    - before_pre_filter(snapshot, pod) -> bool: may mutate the cycle's
      view (reservation restore etc.); True if anything changed
    - pre_filter(state, snapshot, pod) -> Status: admission gates
    - filter(state, snapshot, pod, node) -> Status: per-node feasibility
    - score(state, snapshot, pod, node) -> int: 0..100
    - reserve(state, snapshot, pod, node) -> Status / unreserve(...)
    - permit(state, snapshot, pod, node) -> ("allow"|"wait"|"reject", t)
    - pre_bind(state, snapshot, pod, node) -> Status: final mutations
    """

    name = "Plugin"

    def before_pre_filter(self, state: CycleState, snapshot, pod) -> bool:
        return False

    def after_pre_filter(self, state: CycleState, snapshot, pod) -> None:
        """Correct per-plugin cycle state after every PreFilter ran
        (reference: PreFilterTransformer.AfterPreFilter,
        interface.go:83-85)."""

    def pre_filter(self, state: CycleState, snapshot, pod) -> Status:
        return Status.success()

    def before_filter(self, state: CycleState, snapshot, pod, node):
        """May substitute the (pod, node) the Filter phase sees for this
        node (reference: FilterTransformer.BeforeFilter,
        interface.go:88-92). Return None to leave them unchanged, or a
        ``(pod, node)`` pair."""
        return None

    def filter(self, state: CycleState, snapshot, pod, node) -> Status:
        return Status.success()

    def before_score(self, state: CycleState, snapshot, pod, nodes):
        """May substitute the (pod, feasible nodes) the Score phase sees
        (reference: ScoreTransformer.BeforeScore, interface.go:95-97).
        Return None to leave them unchanged, or a ``(pod, nodes)`` pair."""
        return None

    def score(self, state: CycleState, snapshot, pod, node) -> int:
        return 0

    def score_weight(self) -> int:
        return 1

    def reserve(self, state: CycleState, snapshot, pod, node) -> Status:
        return Status.success()

    def unreserve(self, state: CycleState, snapshot, pod, node) -> None:
        pass

    def permit(self, state: CycleState, snapshot, pod, node) -> Tuple[str, float]:
        return ("allow", 0.0)

    def pre_bind(self, state: CycleState, snapshot, pod, node) -> Status:
        return Status.success()

    def post_filter(self, state: CycleState, snapshot, pod) -> None:
        """Called when every node was filtered out (failure fan-out)."""


@dataclasses.dataclass
class ScheduleOutcome:
    pod_uid: str
    node: Optional[str]
    status: str                  # bound | waiting | unschedulable | error
    reason: str = ""
    scores: Optional[Dict[str, int]] = None  # populated when debug enabled
    #: the cycle's state, returned for *waiting* outcomes so the caller
    #: can roll back Reserve-time holds if the Permit wait later expires
    cycle_state: Optional["CycleState"] = None
    #: *nominated* outcomes: uids of pods that must be evicted before the
    #: nominated node has room (PostFilter preemption)
    victims: Optional[List[str]] = None


class SchedulingFramework:
    """Runs one pod through the full plugin chain (SURVEY.md §3.1)."""

    def __init__(self, plugins: Sequence[Plugin], debug=None,
                 cycle_seed=None):
        self.plugins = list(plugins)
        self.debug = debug
        #: entries copied into every fresh CycleState (per-scheduler
        #: configuration the shared lowering needs, e.g. the LoadAware
        #: aggregated profile)
        self.cycle_seed = dict(cycle_seed or {})

    def schedule_one(
        self, snapshot: ClusterSnapshot, pod: PodSpec
    ) -> ScheduleOutcome:
        # stuck-cycle detection moved to the span-fed watchdog
        # (scheduler/monitor.py reads the trace fabric's open marks);
        # the per-pod host recording the seed kept here is gone
        return self._schedule_one(snapshot, pod)

    def _run_post_filter(self, state, snapshot, pod) -> Optional[ScheduleOutcome]:
        """PostFilter: side effects (gang rejection fan-out) run for every
        plugin; the first preemption nomination wins (reference: framework
        RunPostFilterPlugins)."""
        nomination = None
        for plugin in self.plugins:
            result = plugin.post_filter(state, snapshot, pod)
            if result is not None and nomination is None:
                nomination = result
        if nomination is None:
            return None
        node_name, victims = nomination
        return ScheduleOutcome(
            pod.uid,
            node_name,
            "nominated",
            reason=f"preemption: {len(victims)} victim(s)",
            victims=[v.uid for v in victims],
        )

    def _schedule_one(self, snapshot, pod) -> ScheduleOutcome:
        state = CycleState(self.cycle_seed)

        for plugin in self.plugins:
            plugin.before_pre_filter(state, snapshot, pod)
        after_pre_filter_ran = False

        def run_after_pre_filter():
            # AfterPreFilter runs once, whatever ends the PreFilter phase
            # (framework_extender.go:167-199 runs it on both outcomes)
            nonlocal after_pre_filter_ran
            if not after_pre_filter_ran:
                after_pre_filter_ran = True
                for plugin in self.plugins:
                    plugin.after_pre_filter(state, snapshot, pod)

        for plugin in self.plugins:
            status = plugin.pre_filter(state, snapshot, pod)
            if not status.ok:
                run_after_pre_filter()
                # an unschedulable PreFilter verdict (e.g. quota admission)
                # still reaches PostFilter, exactly as the k8s framework's
                # scheduleOne error path does — this is how ElasticQuota
                # preemption triggers on quota rejection
                nominated = self._run_post_filter(state, snapshot, pod)
                if nominated is not None:
                    return nominated
                return ScheduleOutcome(
                    pod.uid, None, "unschedulable", f"{plugin.name}: {status.reason}"
                )

        run_after_pre_filter()

        feasible: List[NodeSpec] = []
        for node in snapshot.nodes:
            if node.unschedulable:
                continue
            # BeforeFilter transformers may substitute the pod/node view
            filter_pod, filter_node = pod, node
            for plugin in self.plugins:
                replaced = plugin.before_filter(
                    state, snapshot, filter_pod, filter_node
                )
                if replaced is not None:
                    filter_pod, filter_node = replaced
            ok = True
            for plugin in self.plugins:
                status = plugin.filter(state, snapshot, filter_pod, filter_node)
                if not status.ok:
                    if self.debug is not None:
                        self.debug.record_filter(pod.uid, node.name, plugin.name, status)
                    ok = False
                    break
            if ok:
                feasible.append(node)
        if not feasible:
            nominated = self._run_post_filter(state, snapshot, pod)
            if nominated is not None:
                return nominated
            return ScheduleOutcome(pod.uid, None, "unschedulable", "no feasible node")

        # BeforeScore transformers may substitute the pod / feasible set
        score_pod = pod
        for plugin in self.plugins:
            replaced = plugin.before_score(state, snapshot, score_pod, feasible)
            if replaced is not None:
                score_pod, feasible = replaced
        if not feasible:
            # a transformer filtered every candidate away
            nominated = self._run_post_filter(state, snapshot, pod)
            if nominated is not None:
                return nominated
            return ScheduleOutcome(
                pod.uid, None, "unschedulable", "no feasible node after transformers"
            )

        best_node, best_score = None, -1
        all_scores: Dict[str, int] = {}
        for node in feasible:
            total = 0
            for plugin in self.plugins:
                total += plugin.score_weight() * plugin.score(
                    state, snapshot, score_pod, node
                )
            all_scores[node.name] = total
            if total > best_score:
                best_node, best_score = node, total
        if self.debug is not None:
            self.debug.record_scores(pod.uid, all_scores)

        for i, plugin in enumerate(self.plugins):
            status = plugin.reserve(state, snapshot, pod, best_node)
            if not status.ok:
                # unreserve ALL plugins including the failing one (the k8s
                # framework contract: a failing Reserve may have partially
                # mutated state)
                for done in self.plugins[: i + 1]:
                    done.unreserve(state, snapshot, pod, best_node)
                return ScheduleOutcome(
                    pod.uid, None, "unschedulable", f"{plugin.name}: {status.reason}"
                )

        for plugin in self.plugins:
            verdict, _wait = plugin.permit(state, snapshot, pod, best_node)
            if verdict == "wait":
                return ScheduleOutcome(
                    pod.uid, best_node.name, "waiting", cycle_state=state
                )
            if verdict == "reject":
                for done in self.plugins:
                    done.unreserve(state, snapshot, pod, best_node)
                return ScheduleOutcome(
                    pod.uid, None, "unschedulable", f"{plugin.name}: permit rejected"
                )

        for plugin in self.plugins:
            status = plugin.pre_bind(state, snapshot, pod, best_node)
            if not status.ok:
                for done in self.plugins:
                    done.unreserve(state, snapshot, pod, best_node)
                return ScheduleOutcome(
                    pod.uid, None, "error", f"{plugin.name}: {status.reason}"
                )

        return ScheduleOutcome(
            pod.uid,
            best_node.name,
            "bound",
            scores=all_scores if self.debug is not None else None,
        )
