"""Static shape-flow: the lattice/engine half of graftcheck v3.

PRs 13-18 defended against jit recompile storms *empirically* — a
compile ring, warm pools, pre-compiled pod buckets — every one a
counter that trips after the storm happens. This module is the static
half: an interprocedural abstract interpretation over array-shape
provenance that proves, before any code runs, that every dynamic count
feeding a hot jit axis flows through a registered bucket function — so
the reachable aval-signature set is finite and the warm manifest
(docs/DESIGN.md §21) can cover it.

**The lattice.** Every scalar-ish value is abstracted to one of:

- ``constant`` — a literal; contributes one signature.
- ``aligned`` — copied from an existing array's ``.shape``: a width
  that MIRRORS an axis that already exists adds no NEW signature
  dimension (``jnp.zeros(x.shape[0])`` compiles once per shape of
  ``x``, which some other flow already owns). Arithmetic over an
  aligned value FORFEITS alignment: a derived count is a new surface.
- ``bucketed(fn)`` — passed through a registered bucket function
  (``pow2_quarter_bucket`` and family): finite image under the config
  bounds, so a finite signature contribution. ``bucket(n) - n`` (the
  pad-remainder idiom every ``_pad_*`` helper uses) stays bucketed:
  the RESULTING axis is the bucket, whatever the remainder.
- ``raw-dynamic`` — derived from ``len()`` of a python collection, a
  comprehension, or arithmetic over the above: one compiled program
  per distinct value. Raw reaching a device-width sink is the exact
  shape of the pre-PR 8 / pre-PR 16 recompile storms.

**Interprocedural.** Function summaries (return kind) and parameter
taints (join of argument kinds over every resolved call site) run to a
bounded fixpoint over the v2 call graph, so ``n_real = len(pods)``
three frames above a ``jnp.pad`` still convicts. Functions reachable
from a ``jax.jit``/``jax.vmap`` root are TRACED scope: inside a trace,
``.shape`` is static per-signature and width sinks create no new
surface, so traced bodies are exempt (the surface is the call
boundary, which the signature-space pass and the runtime sentinel
own).

**Sinks** (host-side, scope-matched): ``jnp.zeros/ones/full/empty``
widths, ``jnp.pad`` pad_widths, ``jax.ShapeDtypeStruct`` shapes, and
``jnp.asarray/array`` of a comprehension-built sequence. Host ``np.*``
staging arrays are deliberately NOT sinks: the host world is lowered
at cluster size by design and bucketed at the device boundary — which
is exactly the boundary this pass polices.

Resolution is under-approximate like the rest of graftcheck: an
unresolvable call contributes nothing, unknown values never convict.

Stdlib-only (``ast``), like the rest of the engine.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from koordinator_tpu.analysis.graftcheck.callgraph import Program
from koordinator_tpu.analysis.graftcheck.engine import (
    attr_chain,
    module_matches,
)

# -- the lattice -------------------------------------------------------------

CONSTANT = "constant"
ALIGNED = "aligned"
BUCKETED = "bucketed"
RAW = "raw-dynamic"

#: join severity: raw convicts, bucketed sanctions, aligned mirrors
_ORDER = {CONSTANT: 0, ALIGNED: 1, BUCKETED: 2, RAW: 3}


@dataclasses.dataclass(frozen=True)
class Sv:
    """One abstract shape value."""

    kind: str
    origin: str = ""      # bucket fn | raw source description

    def __repr__(self):
        return f"{self.kind}({self.origin})" if self.origin else self.kind


_CONST = Sv(CONSTANT)


def join(values: Sequence[Optional[Sv]]) -> Optional[Sv]:
    """Worst-of join; None (unknown) is absorbing only when alone."""
    best: Optional[Sv] = None
    for v in values:
        if v is None:
            continue
        if best is None or _ORDER[v.kind] > _ORDER[best.kind]:
            best = v
    return best


@dataclasses.dataclass(frozen=True)
class BucketFn:
    """One registered bucket sanctioner.

    ``name`` is the bare callable name as written at call sites (the
    import-alias-proof fallback); ``qualname`` + ``path`` pin the real
    definition so the census can flag a registry entry whose function
    no longer exists. A call to a sanctioner returns ``bucketed``
    whatever its arguments; its own body is where raw legitimately
    becomes bucketed, so sanctioner bodies are never sink-scanned when
    ``exempt_body`` is set (the pure int->int computers); the padding
    helpers (``_pad_pods``/``_pad_resv``) keep ``exempt_body=False`` —
    their bodies are HELD to the discipline, which is what makes
    stripping a bucket call inside them machine-detectable."""

    name: str
    path: str = ""
    qualname: str = ""
    exempt_body: bool = False

    @property
    def key(self) -> str:
        return f"{self.path}::{self.qualname}" if self.path else ""


#: builtins folded like arithmetic-free joins (max(8, bucket(n)) stays
#: bucketed; max of raws stays raw)
_JOIN_BUILTINS = frozenset({"max", "min", "int", "abs", "round", "sum"})

#: width-sink producers: chain suffix -> which argument is the width
_ZEROS_FAMILY = frozenset({"zeros", "ones", "full", "empty"})


def _is_jnp(chain: str) -> bool:
    head = chain.split(".")[0]
    return head in ("jnp",) or chain.startswith("jax.numpy.")


class ShapeFlowEngine:
    """Program-wide shape-provenance analysis.

    Construction runs the full fixpoint (the expensive part), so
    :class:`~.rules.shape_flow.BucketFlowRule` memoizes the instance
    on the Program per bucket registry — repeated check runs over one
    Program pay one analysis."""

    #: interprocedural fixpoint rounds (summaries/taints stabilize in
    #: 2 on this repo; 3 bounds pathological call chains)
    ROUNDS = 3

    def __init__(self, program: Program, buckets: Sequence[BucketFn]):
        self.program = program
        self.buckets = tuple(buckets)
        self._bucket_by_key = {b.key: b for b in buckets if b.key}
        self._bucket_by_name = {b.name: b for b in buckets}
        #: function key -> return-value summary
        self.summaries: Dict[str, Sv] = {
            b.key: Sv(BUCKETED, b.name) for b in buckets if b.key
        }
        #: function key -> {param name -> Sv}
        self.param_taint: Dict[str, Dict[str, Sv]] = {}
        self.traced: Set[str] = self._traced_closure()
        for _ in range(self.ROUNDS):
            self._propagate()

    # -- traced scope --------------------------------------------------------

    def _jit_roots(self) -> Set[str]:
        """Function keys passed to ``jax.jit``/``jax.vmap``/``pjit``
        anywhere in the program — the trace entry points."""
        roots: Set[str] = set()
        for module in self.program.modules:
            table = self.program.module_table(module.path)
            if table is None:
                continue
            for node in ast.walk(module.tree):
                if not isinstance(node, ast.Call) or not node.args:
                    continue
                chain = attr_chain(node.func) or ""
                if chain.split(".")[-1] not in ("jit", "vmap", "pjit",
                                                "shard_map"):
                    continue
                target = node.args[0]
                name = target.id if isinstance(target, ast.Name) else None
                if name is None:
                    continue
                sym = table.symbols.get(name)
                if sym is not None and sym[0] == "func":
                    roots.add(sym[1])
                    continue
                imp = table.imports.get(name)
                if imp is not None and imp[0] == "symbol":
                    target_mod = self.program.by_dotted.get(imp[1])
                    if target_mod is not None:
                        t2 = self.program.module_table(target_mod.path)
                        sym2 = t2.symbols.get(imp[2]) if t2 else None
                        if sym2 is not None and sym2[0] == "func":
                            roots.add(sym2[1])
        # decorator-form roots: ``@jax.jit`` and
        # ``@functools.partial(jax.jit, ...)`` (ops/pallas_binpack.py)
        for key, info in self.program.functions.items():
            for dec in getattr(info.node, "decorator_list", []):
                chain = attr_chain(dec) or ""
                if chain.split(".")[-1] in ("jit", "pjit"):
                    roots.add(key)
                elif isinstance(dec, ast.Call):
                    dchain = attr_chain(dec.func) or ""
                    if dchain.split(".")[-1] in ("jit", "pjit"):
                        roots.add(key)
                    elif dchain.split(".")[-1] == "partial" and dec.args:
                        inner = attr_chain(dec.args[0]) or ""
                        if inner.split(".")[-1] in ("jit", "pjit"):
                            roots.add(key)
        return roots

    def _traced_closure(self) -> Set[str]:
        seen = set()
        work = list(self._jit_roots())
        while work:
            key = work.pop()
            if key in seen:
                continue
            seen.add(key)
            for site in self.program.callees(key):
                if site.callee not in seen:
                    work.append(site.callee)
        return seen

    # -- fixpoint ------------------------------------------------------------

    def _propagate(self) -> None:
        new_taint: Dict[str, Dict[str, Sv]] = {}
        new_summaries: Dict[str, Sv] = dict(self.summaries)
        for key, info in self.program.functions.items():
            walker = _FunctionWalker(self, info, collect=False)
            walker.run()
            if key not in self._bucket_by_key:
                if walker.return_value is not None:
                    new_summaries[key] = walker.return_value
                else:
                    new_summaries.pop(key, None)
            for callee, params in walker.arg_kinds:
                slot = new_taint.setdefault(callee, {})
                for pname, sv in params.items():
                    slot[pname] = join([slot.get(pname), sv])
        # registered sanctioners keep their forced summary whatever
        # their bodies compute — that is what "sanctioner" means
        for b in self.buckets:
            if b.key:
                new_summaries[b.key] = Sv(BUCKETED, b.name)
        self.param_taint = new_taint
        self.summaries = new_summaries

    # -- the rule entry point ------------------------------------------------

    def violations(self, scope: Sequence[str]):
        """(path, line, col, qualname, symbol, message) sink hits for
        every non-traced, non-exempt function in ``scope``."""
        out = []
        for key, info in sorted(self.program.functions.items()):
            if not module_matches(info.path, scope):
                continue
            if key in self.traced:
                continue
            bucket = self._bucket_by_key.get(key)
            if bucket is not None and bucket.exempt_body:
                continue
            walker = _FunctionWalker(self, info, collect=True)
            walker.run()
            out.extend(walker.violations)
        return out

    # -- shared resolution helpers -------------------------------------------

    def resolve_call(self, keys: Sequence[str], call: ast.Call
                     ) -> Optional[str]:
        """The callee key of ``call`` as the v2 graph resolved it (the
        graph stores edges per caller; match by node identity). The
        walker passes its scope-key stack so calls inside nested defs —
        which the graph attributes to the NESTED key — still resolve."""
        for key in keys:
            for site in self.program.callees(key):
                if site.node is call:
                    return site.callee
        return None

    def bucket_for_call(self, keys: Sequence[str], call: ast.Call,
                        callee: Optional[str] = None
                        ) -> Optional[BucketFn]:
        """``callee`` lets the walker hand in the key it already
        resolved — resolve_call is a linear scan over the caller's
        call sites, and running it twice per call node doubled the
        dominant cost of the pass."""
        if callee is None:
            callee = self.resolve_call(keys, call)
        if callee is not None and callee in self._bucket_by_key:
            return self._bucket_by_key[callee]
        chain = attr_chain(call.func) or ""
        return self._bucket_by_name.get(chain.split(".")[-1])


class _FunctionWalker:
    """One function's abstract interpretation (single forward pass in
    statement order; loops are walked once — under-approximate)."""

    def __init__(self, engine: ShapeFlowEngine, info, collect: bool):
        self.engine = engine
        self.info = info
        self.collect = collect
        self.violations: List[Tuple[str, int, int, str, str, str]] = []
        #: (callee key, {param name -> Sv}) per resolved call site
        self.arg_kinds: List[Tuple[str, Dict[str, Sv]]] = []
        self.return_value: Optional[Sv] = None
        self._returns_seen = 0

    # -- entry ---------------------------------------------------------------

    def run(self) -> None:
        env: Dict[str, Sv] = {}
        taint = self.engine.param_taint.get(self.info.key, {})
        fn_node = self.info.node
        args = fn_node.args
        for a in list(args.posonlyargs) + list(args.args) \
                + list(args.kwonlyargs):
            sv = taint.get(a.arg)
            if sv is not None:
                env[a.arg] = sv
        #: scope-key stack: the call graph attributes nested-def bodies
        #: to the nested function's own key
        self._keys: List[str] = [self.info.key]
        #: bare name -> nested function key (the call graph cannot
        #: resolve calls to nested defs; the walker can)
        self._nested: Dict[str, str] = {}
        self._walk_body(fn_node.body, env, self.info.qualname)

    # -- statements ----------------------------------------------------------

    def _walk_body(self, body, env: Dict[str, Sv], qual: str) -> None:
        for stmt in body:
            self._walk_stmt(stmt, env, qual)

    def _walk_stmt(self, stmt, env, qual) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # nested def: closure reads the enclosing env as it stands
            # at the def site; sinks inside report under the nested
            # qualname (allowlist-stable, like qualname_map's labels)
            nested_qual = f"{qual}.{stmt.name}"
            nested_key = f"{self.info.path}::{nested_qual}"
            if nested_key in self.engine.program.functions:
                self._nested[stmt.name] = nested_key
            nested_env = dict(env)
            # the nested fn's own params shadow closure names and carry
            # their interprocedural taints (call sites resolve to the
            # NESTED key)
            taint = self.engine.param_taint.get(nested_key, {})
            nargs = stmt.args
            for a in list(nargs.posonlyargs) + list(nargs.args) \
                    + list(nargs.kwonlyargs):
                sv = taint.get(a.arg)
                if sv is not None:
                    nested_env[a.arg] = sv
                else:
                    nested_env.pop(a.arg, None)
            self._keys.append(nested_key)
            # the nested body is walked for SINK collection only: its
            # returns summarize under the nested function's own key
            # (its own fixpoint pass), and letting them join into the
            # enclosing summary convicts innocent callers of the outer
            # function (or launders a raw outer return to unknown)
            saved = (self.return_value, self._returns_seen)
            self._walk_body(stmt.body, nested_env, nested_qual)
            self.return_value, self._returns_seen = saved
            self._keys.pop()
            return
        if isinstance(stmt, ast.ClassDef):
            return
        if isinstance(stmt, ast.Return):
            if stmt.value is not None:
                self._returns_seen += 1
                sv = self._eval(stmt.value, env, qual)
                if self._returns_seen == 1:
                    self.return_value = sv
                else:
                    self.return_value = join([self.return_value, sv]) \
                        if sv is not None and self.return_value is not None \
                        else None
            return
        if isinstance(stmt, ast.AugAssign):
            # ``n += 1`` is ``n = n <op> 1``: combine the target's
            # CURRENT value with the RHS under the same arithmetic
            # semantics as _binop — a raw count incremented in place
            # stays raw (overwriting with the RHS-only value would let
            # ``n = len(pods); n += 1`` escape what
            # ``n = len(pods) + 1`` convicts)
            rhs = self._eval(stmt.value, env, qual)
            if isinstance(stmt.target, ast.Name):
                container = isinstance(
                    stmt.value, (ast.List, ast.Tuple, ast.ListComp)
                )
                sv = self._arith(
                    stmt.op, env.get(stmt.target.id), rhs, container
                )
                if sv is not None:
                    env[stmt.target.id] = sv
                else:
                    env.pop(stmt.target.id, None)
            return
        if isinstance(stmt, (ast.Assign, ast.AnnAssign)):
            value = stmt.value
            if value is None:
                return
            sv = self._eval(value, env, qual)
            targets = stmt.targets if isinstance(stmt, ast.Assign) \
                else [stmt.target]
            for t in targets:
                names = [t]
                if isinstance(t, (ast.Tuple, ast.List)):
                    names = list(t.elts)
                for n in names:
                    if isinstance(n, ast.Name):
                        if sv is not None:
                            env[n.id] = sv
                        else:
                            env.pop(n.id, None)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._eval(stmt.test, env, qual)
            self._walk_body(stmt.body, env, qual)
            self._walk_body(stmt.orelse, env, qual)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._eval(stmt.iter, env, qual)
            self._walk_body(stmt.body, env, qual)
            self._walk_body(stmt.orelse, env, qual)
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            for item in stmt.items:
                self._eval(item.context_expr, env, qual)
            self._walk_body(stmt.body, env, qual)
            return
        if isinstance(stmt, ast.Try):
            for block in (stmt.body, stmt.orelse, stmt.finalbody):
                self._walk_body(block, env, qual)
            for handler in stmt.handlers:
                self._walk_body(handler.body, env, qual)
            return
        if isinstance(stmt, ast.Expr):
            self._eval(stmt.value, env, qual)
            return
        for child in ast.iter_child_nodes(stmt):
            if isinstance(child, ast.expr):
                self._eval(child, env, qual)

    # -- expressions ---------------------------------------------------------

    def _eval(self, node, env, qual) -> Optional[Sv]:
        if isinstance(node, ast.Constant):
            return _CONST if isinstance(node.value, (int, bool)) else None
        if isinstance(node, ast.Name):
            return env.get(node.id)
        if isinstance(node, ast.Attribute):
            self._eval(node.value, env, qual)
            if node.attr == "shape":
                return Sv(ALIGNED, ".shape")
            if node.attr == "ndim":
                return _CONST  # rank is structural, never a count
            if node.attr == "size":
                return Sv(RAW, ".size")  # a product of dims is derived
            return None
        if isinstance(node, ast.Subscript):
            base = self._eval(node.value, env, qual)
            self._eval(node.slice, env, qual)
            return base
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return join([self._eval(e, env, qual) for e in node.elts])
        if isinstance(node, (ast.ListComp, ast.SetComp,
                             ast.GeneratorExp, ast.DictComp)):
            # a comprehension's LENGTH is data-dependent: the sequence
            # itself is a raw-dynamic axis if it ever becomes one. The
            # element expression still gets walked (calls inside it
            # feed the interprocedural taints and the sink scan).
            for gen in node.generators:
                self._eval(gen.iter, env, qual)
                for cond in gen.ifs:
                    self._eval(cond, env, qual)
            if isinstance(node, ast.DictComp):
                self._eval(node.key, env, qual)
                self._eval(node.value, env, qual)
            else:
                self._eval(node.elt, env, qual)
            return Sv(RAW, "comprehension")
        if isinstance(node, ast.IfExp):
            self._eval(node.test, env, qual)
            return join([self._eval(node.body, env, qual),
                         self._eval(node.orelse, env, qual)])
        if isinstance(node, ast.BoolOp):
            return join([self._eval(v, env, qual) for v in node.values])
        if isinstance(node, ast.Compare):
            self._eval(node.left, env, qual)
            for c in node.comparators:
                self._eval(c, env, qual)
            return None
        if isinstance(node, ast.UnaryOp):
            return self._eval(node.operand, env, qual)
        if isinstance(node, ast.BinOp):
            return self._binop(node, env, qual)
        if isinstance(node, ast.Starred):
            return self._eval(node.value, env, qual)
        if isinstance(node, ast.Lambda):
            return None
        if isinstance(node, ast.Call):
            return self._call(node, env, qual)
        for child in ast.iter_child_nodes(node):
            if isinstance(child, ast.expr):
                self._eval(child, env, qual)
        return None

    def _binop(self, node: ast.BinOp, env, qual) -> Optional[Sv]:
        left = self._eval(node.left, env, qual)
        right = self._eval(node.right, env, qual)
        container = isinstance(
            node.left, (ast.List, ast.Tuple, ast.ListComp)
        ) or isinstance(node.right, (ast.List, ast.Tuple, ast.ListComp))
        return self._arith(node.op, left, right, container)

    @staticmethod
    def _arith(op, left: Optional[Sv], right: Optional[Sv],
               container: bool) -> Optional[Sv]:
        if container:
            # list/tuple concat or repeat: element taints join, no
            # arithmetic escalation (``[(0, pad)] + [(0, 0)] * k``)
            return join([left, right])
        # the pad-remainder idiom: bucket(n) - n keeps the bucket —
        # the resulting axis IS the bucket, whatever the remainder
        if isinstance(op, ast.Sub) and left is not None \
                and left.kind == BUCKETED:
            return Sv(BUCKETED, f"{left.origin}-remainder")
        joined = join([left, right])
        if joined is None:
            return None
        if joined.kind == RAW:
            return joined
        if joined.kind == BUCKETED:
            return joined
        if joined.kind == ALIGNED:
            # arithmetic over an aligned width forfeits alignment: a
            # DERIVED count is a new signature surface
            return Sv(RAW, f"arith({joined.origin})")
        return _CONST

    def _call(self, node: ast.Call, env, qual) -> Optional[Sv]:
        chain = attr_chain(node.func) or ""
        tail = chain.split(".")[-1]
        arg_vals = [self._eval(a, env, qual) for a in node.args]
        kw_vals = {
            k.arg: self._eval(k.value, env, qual)
            for k in node.keywords if k.arg is not None
        }
        for k in node.keywords:
            if k.arg is None:
                self._eval(k.value, env, qual)

        engine = self.engine
        if chain == "len":
            return Sv(RAW, "len()")
        if isinstance(node.func, ast.Attribute) \
                and node.func.attr == "bit_length":
            recv = self._eval(node.func.value, env, qual)
            if recv is not None and recv.kind in (ALIGNED, RAW):
                return Sv(RAW, "arith(bit_length)")
            return recv

        callee = engine.resolve_call(self._keys, node)
        if callee is None and isinstance(node.func, ast.Name):
            callee = self._nested.get(node.func.id)
        if callee is not None:
            # record argument taints BEFORE the sanctioner return: the
            # padding helpers are sanctioners whose PARAMS carry the
            # raw counts their bodies are held accountable for
            self._record_args(callee, node, arg_vals, kw_vals)

        bucket = engine.bucket_for_call(self._keys, node, callee)
        if bucket is not None:
            return Sv(BUCKETED, bucket.name)

        if self.collect:
            self._check_sinks(chain, tail, node, arg_vals, kw_vals, qual)

        if chain in _JOIN_BUILTINS:
            return join(arg_vals + list(kw_vals.values()))
        if callee is not None:
            return engine.summaries.get(callee)
        return None

    def _record_args(self, callee: str, node: ast.Call,
                     arg_vals, kw_vals) -> None:
        info = self.engine.program.functions.get(callee)
        if info is None:
            return
        fn_args = info.node.args
        params = [a.arg for a in list(fn_args.posonlyargs)
                  + list(fn_args.args)]
        if params and params[0] in ("self", "cls") \
                and not isinstance(node.func, ast.Name):
            params = params[1:]
        elif params and params[0] in ("self", "cls") \
                and info.qualname.endswith("__init__"):
            params = params[1:]
        mapped: Dict[str, Sv] = {}
        for pname, sv in zip(params, arg_vals):
            if sv is not None:
                mapped[pname] = sv
        kw_names = {a.arg for a in fn_args.args} \
            | {a.arg for a in fn_args.kwonlyargs}
        for k, sv in kw_vals.items():
            if sv is not None and k in kw_names:
                mapped[k] = sv
        if mapped:
            self.arg_kinds.append((callee, mapped))

    # -- sinks ---------------------------------------------------------------

    def _flag(self, node, qual: str, symbol: str, raw: Sv,
              what: str) -> None:
        self.violations.append((
            self.info.path, node.lineno, node.col_offset, qual, symbol,
            f"{what} is {raw!r}: a raw-dynamic count reaching a "
            f"jit-visible axis is one compiled program per value — "
            f"route it through the registered bucket family",
        ))

    def _first_raw(self, values) -> Optional[Sv]:
        for v in values:
            if v is not None and v.kind == RAW:
                return v
        return None

    def _check_sinks(self, chain, tail, node, arg_vals, kw_vals,
                     qual) -> None:
        if _is_jnp(chain) and tail in _ZEROS_FAMILY:
            width = [arg_vals[0]] if arg_vals else []
            if "shape" in kw_vals:
                width.append(kw_vals["shape"])
            raw = self._first_raw(width)
            if raw is not None:
                self._flag(node, qual, chain,
                           raw, f"the shape of {chain}()")
            return
        if _is_jnp(chain) and tail == "pad":
            width = [arg_vals[1]] if len(arg_vals) > 1 else []
            if "pad_width" in kw_vals:
                width.append(kw_vals["pad_width"])
            raw = self._first_raw(width)
            if raw is not None:
                self._flag(node, qual, chain,
                           raw, f"the pad widths of {chain}()")
            return
        if tail == "ShapeDtypeStruct":
            width = [arg_vals[0]] if arg_vals else []
            if "shape" in kw_vals:
                width.append(kw_vals["shape"])
            raw = self._first_raw(width)
            if raw is not None:
                self._flag(node, qual, chain,
                           raw, "the shape of ShapeDtypeStruct")
            return
        if _is_jnp(chain) and tail in ("asarray", "array"):
            raw = self._first_raw(arg_vals[:1])
            if raw is not None and raw.origin == "comprehension":
                self._flag(node, qual, chain, raw,
                           f"the sequence materialized by {chain}()")


# -- binding / adoption census (shared by the three v3 rules) ----------------

@dataclasses.dataclass
class ObservedBinding:
    """One ``DEVICE_OBS.jit("name", jax.jit(f, ...))`` site."""

    name: str
    path: str
    line: int
    qualname: str             # enclosing scope ("<module>" | "Class.__init__")
    target: str               # assignment target chain ("self._solve", "_jit_x")
    wrapped: str              # the jitted callable's name ("" if opaque)
    static_argnames: Tuple[str, ...]
    has_static_argnums: bool
    donates: bool


@dataclasses.dataclass
class Adoption:
    """One ``WARM_POOL.adopt(binding, fun, config_argpos=N)`` site."""

    binding: str              # resolved DEVICE_OBS binding name ("" if opaque)
    target: str               # the raw first-arg chain
    path: str
    line: int


def _tuple_of_strs(node) -> Tuple[str, ...]:
    if isinstance(node, (ast.Tuple, ast.List)):
        return tuple(
            e.value for e in node.elts
            if isinstance(e, ast.Constant) and isinstance(e.value, str)
        )
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return (node.value,)
    return ()


def find_observed_bindings(program: Program,
                           obs_names: Sequence[str] = ("DEVICE_OBS",),
                           ) -> List[ObservedBinding]:
    """Every ``DEVICE_OBS.jit`` binding in the program, with the jit
    factory's static/donate declarations when the second argument is a
    literal ``jax.jit(...)`` call. Memoized on the Program instance
    (immutable once built): the signature-space and warm-coverage
    passes both census the whole universe, and without the memo every
    check run walked every module's AST twice for identical results."""
    from koordinator_tpu.analysis.graftcheck.engine import qualname_map

    cached = getattr(program, "_shapeflow_bindings", None)
    if cached is not None and cached[0] == tuple(obs_names):
        return cached[1]

    out: List[ObservedBinding] = []
    for module in program.modules:
        qmap = qualname_map(module.tree)
        for node in ast.walk(module.tree):
            target_node = None
            if isinstance(node, ast.Assign) and len(node.targets) == 1:
                call = node.value
                target_node = node.targets[0]
            elif isinstance(node, ast.Return):
                # factory form: ``return DEVICE_OBS.jit("name", ...)``
                # (parallel/mesh.py's shard_solver)
                call = node.value
            else:
                continue
            if not isinstance(call, ast.Call):
                continue
            chain = attr_chain(call.func) or ""
            parts = chain.split(".")
            if len(parts) < 2 or parts[-1] != "jit" \
                    or parts[-2] not in obs_names:
                continue
            if not call.args or not isinstance(call.args[0], ast.Constant):
                continue
            name = call.args[0].value
            target = (attr_chain(target_node) or "") \
                if target_node is not None else ""
            wrapped = ""
            statics: Tuple[str, ...] = ()
            has_argnums = False
            donates = False
            if len(call.args) > 1 and isinstance(call.args[1], ast.Call):
                jit_call = call.args[1]
                if jit_call.args and isinstance(jit_call.args[0], ast.Name):
                    wrapped = jit_call.args[0].id
                for kw in jit_call.keywords:
                    if kw.arg == "static_argnames":
                        statics = _tuple_of_strs(kw.value)
                    elif kw.arg == "static_argnums":
                        has_argnums = bool(
                            not isinstance(kw.value, ast.Tuple)
                            or kw.value.elts
                        )
                    elif kw.arg == "donate_argnums":
                        donates = bool(
                            not isinstance(kw.value, ast.Tuple)
                            or kw.value.elts
                        )
            out.append(ObservedBinding(
                name=name, path=module.path, line=node.lineno,
                qualname=qmap.get(id(node), "<module>"), target=target,
                wrapped=wrapped, static_argnames=statics,
                has_static_argnums=has_argnums, donates=donates,
            ))
    program._shapeflow_bindings = (tuple(obs_names), out)
    return out


def find_adoptions(program: Program,
                   pool_names: Sequence[str] = ("WARM_POOL",),
                   bindings: Optional[Sequence[ObservedBinding]] = None,
                   ) -> List[Adoption]:
    """Every warm-pool adopt site, with the first argument resolved to
    its DEVICE_OBS binding name via same-module assignment targets.
    Memoized like :func:`find_observed_bindings` (identity-keyed on
    the bindings list, which the memo retains)."""
    if bindings is None:
        bindings = find_observed_bindings(program)
    cached = getattr(program, "_shapeflow_adoptions", None)
    if cached is not None and cached[0] is bindings \
            and cached[1] == tuple(pool_names):
        return cached[2]
    by_module: Dict[str, Dict[str, str]] = {}
    for b in bindings:
        # return-factory bindings have no assignment target — mapping
        # their "" would let any OPAQUE adopt expression (attr_chain
        # -> "") in the same module silently resolve to a factory
        # binding, suppressing the opaque-adoption finding AND faking
        # the factory as adopted
        if b.target:
            by_module.setdefault(b.path, {})[b.target] = b.name
    out: List[Adoption] = []
    for module in program.modules:
        targets = by_module.get(module.path, {})
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func) or ""
            parts = chain.split(".")
            if len(parts) < 2 or parts[-1] != "adopt" \
                    or parts[-2] not in pool_names:
                continue
            if not node.args:
                continue
            target = attr_chain(node.args[0]) or ""
            binding = targets.get(target, "") if target else ""
            out.append(Adoption(
                binding=binding, target=target,
                path=module.path, line=node.lineno,
            ))
    program._shapeflow_adoptions = (bindings, tuple(pool_names), out)
    return out
