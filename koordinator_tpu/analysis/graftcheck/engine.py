"""The graftcheck rule engine: file model, allowlist, runner.

Everything here is stdlib-only (``ast`` + ``re``): the checker must run
on any box the repo runs on, with no dependency the container doesn't
already have (Python 3.10 has no ``tomllib``, hence the strict-subset
TOML reader below).
"""

from __future__ import annotations

import ast
import dataclasses
import fnmatch
import json
import re
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Tuple


@dataclasses.dataclass(frozen=True)
class Violation:
    """One rule hit, addressable by (rule, path, func, symbol).

    ``line`` is reporting detail only — allowlist entries deliberately
    match on the enclosing function, not line numbers, so entries
    survive unrelated edits above them.
    """

    rule: str
    path: str          # repo-relative, posix separators
    line: int
    col: int
    func: str          # enclosing qualname ("Class.method" | "<module>")
    symbol: str        # the offending construct ("jax.device_get", ...)
    message: str

    def format(self) -> str:
        return (
            f"{self.path}:{self.line}:{self.col}: [{self.rule}] "
            f"{self.message} (in {self.func})"
        )

    def as_dict(self) -> Dict[str, object]:
        return dataclasses.asdict(self)


@dataclasses.dataclass
class AllowEntry:
    """One justified exception from ``graftcheck.toml``.

    Matches a violation when rule and path are equal, ``func`` is equal
    or ``"*"``, ``symbol`` (when set) is equal, and ``detail`` (when
    set) is a substring of the violation message. ``reason`` is
    mandatory: an allowlist entry without a written justification is
    itself reported as a violation.
    """

    rule: str
    path: str
    func: str = "*"
    symbol: str = ""
    detail: str = ""
    reason: str = ""
    lineno: int = 0
    used: bool = dataclasses.field(default=False, compare=False)

    def matches(self, v: Violation) -> bool:
        return (
            self.rule == v.rule
            and self.path == v.path
            and self.func in ("*", v.func)
            and (not self.symbol or self.symbol == v.symbol)
            and (not self.detail or self.detail in v.message)
        )


_ALLOW_KEYS = {"rule", "path", "func", "symbol", "detail", "reason"}
_KV_RE = re.compile(r'^([A-Za-z_][A-Za-z0-9_]*)\s*=\s*"([^"]*)"\s*$')


def load_allowlist(path: Path) -> List[AllowEntry]:
    """Read the ``[[allow]]`` entries from a strict TOML subset.

    Supported syntax: comments, blank lines, ``[[allow]]`` headers, and
    ``key = "double-quoted string"`` pairs (no escapes). Anything else
    is an error — the allowlist is an audited artifact, not a config
    playground.
    """
    entries: List[AllowEntry] = []
    current: Optional[Dict[str, object]] = None
    if not path.exists():
        return entries
    for lineno, raw in enumerate(path.read_text().splitlines(), 1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[allow]]":
            current = {"lineno": lineno}
            entries.append(current)  # type: ignore[arg-type]
            continue
        m = _KV_RE.match(line)
        if m is None or current is None:
            raise ValueError(
                f"{path.name}:{lineno}: unsupported allowlist syntax: {raw!r}"
            )
        key = m.group(1)
        if key not in _ALLOW_KEYS:
            raise ValueError(
                f"{path.name}:{lineno}: unknown allowlist key {key!r}"
            )
        current[key] = m.group(2)
    out = []
    for e in entries:
        missing = {"rule", "path"} - set(e)
        if missing:
            raise ValueError(
                f"{path.name}:{e['lineno']}: allowlist entry missing "
                f"{sorted(missing)}"
            )
        out.append(AllowEntry(**e))  # type: ignore[arg-type]
    return out


@dataclasses.dataclass
class ModuleFile:
    """One parsed source file handed to every rule."""

    path: str                  # repo-relative, posix separators
    tree: ast.Module
    source: str

    def matches(self, globs: Sequence[str]) -> bool:
        return module_matches(self.path, globs)


def module_matches(path: str, globs: Sequence[str]) -> bool:
    """THE scope predicate — every rule that narrows by path glob uses
    this one definition so a glob-semantics change lands everywhere."""
    return any(fnmatch.fnmatch(path, g) for g in globs)


def load_module(file_path: Path, rel_path: str) -> ModuleFile:
    source = file_path.read_text()
    return ModuleFile(
        path=rel_path, tree=ast.parse(source, filename=rel_path),
        source=source,
    )


#: the repo-root scripts in the analysis universe (ISSUE 15): the
#: bench scan legs build jit-visible worlds too, and a recompile storm
#: seeded there poisons the trajectory records the budgets gate on.
#: An EXPLICIT list, not a glob — an untracked scratch file at the
#: root must never enter the universe (a syntax error there would fail
#: the checker and error every sentinel-armed suite at arming time).
ROOT_SCRIPTS = ("__graft_entry__.py", "bench.py")


def iter_repo_modules(root: Path, package: str = "koordinator_tpu"
                      ) -> Iterable[ModuleFile]:
    """Every ``.py`` file under ``root/package`` plus the declared
    repo-root scripts (:data:`ROOT_SCRIPTS`) — the checker's universe;
    rules narrow by glob. Syntax errors propagate — a file the checker
    can't parse is a finding, not a skip."""
    pkg = root / package
    for file_path in sorted(pkg.rglob("*.py")):
        rel = file_path.relative_to(root).as_posix()
        yield load_module(file_path, rel)
    for name in ROOT_SCRIPTS:
        file_path = root / name
        if file_path.is_file():
            yield load_module(file_path, name)


def qualname_map(tree: ast.Module) -> Dict[int, str]:
    """``id(node) -> enclosing scope qualname`` for every node, so rules
    that walk with ``ast.walk`` still report allowlist-stable ``func``
    fields."""
    mapping: Dict[int, str] = {}

    def visit(node: ast.AST, scopes: List[str]) -> None:
        label = ".".join(scopes) if scopes else "<module>"
        for child in ast.iter_child_nodes(node):
            mapping[id(child)] = label
            if isinstance(
                child,
                (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef),
            ):
                visit(child, scopes + [child.name])
            else:
                visit(child, scopes)

    visit(tree, [])
    return mapping


def attr_chain(node: ast.AST) -> Optional[str]:
    """``a.b.c`` as a dotted string for simple Name/Attribute chains,
    else None (calls, subscripts and literals break the chain)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def run_checks_timed(
    modules: Iterable[ModuleFile],
    rules: Sequence,
    allowlist: Sequence[AllowEntry] = (),
    changed: Optional[Sequence[str]] = None,
) -> Tuple[List[Violation], List[Violation], Dict[str, Dict[str, float]]]:
    """Run ``rules`` over ``modules``; returns ``(violations,
    suppressed, rule_stats)`` where ``rule_stats[name]`` carries the
    rule's wall seconds and violation count. Engine-level findings ride
    the same stream: an allowlist entry with no written reason, and a
    stale entry that no current violation needs, are violations too
    (the allowlist must not rot into a blanket mute).

    Two rule shapes coexist: local rules expose ``check(module)``;
    whole-program rules expose ``check_program(program)`` and receive a
    :class:`~koordinator_tpu.analysis.graftcheck.callgraph.Program`
    built once over the full module set.

    ``changed`` (repo-relative paths) is the incremental mode: local
    rules scan only the changed modules, while whole-program rules
    still analyze the FULL program (their properties span files a diff
    never names). Allowlist staleness is then only judged for entries
    an incremental run could have re-validated — whole-program rules,
    or local-rule entries on a changed path."""
    import time as _time

    module_list = list(modules)
    changed_set = set(changed) if changed is not None else None
    raw: List[Violation] = []
    seen = set()
    stats: Dict[str, Dict[str, float]] = {}
    program_rule_names = set()
    program = None
    if any(hasattr(r, "check_program") for r in rules):
        # built once, lazily: a local-rules-only run (--rule=dead-import,
        # legacy run_checks callers with rule subsets) never pays the
        # cross-module resolution. The build is real work — reported
        # under its own stats key so JSON wall times sum to the truth.
        from koordinator_tpu.analysis.graftcheck.callgraph import (
            build_program,
        )

        t0 = _time.perf_counter()
        program = build_program(module_list)
        stats["<call-graph>"] = {
            "wall_s": _time.perf_counter() - t0, "found": 0,
        }
    for rule in rules:
        t0 = _time.perf_counter()
        found: List[Violation] = []
        if hasattr(rule, "check_program"):
            program_rule_names.add(rule.name)
            found.extend(rule.check_program(program))
        else:
            for module in module_list:
                if changed_set is not None \
                        and module.path not in changed_set:
                    continue
                found.extend(rule.check(module))
        kept = 0
        for v in found:
            key = (v.rule, v.path, v.line, v.col, v.symbol)
            if key not in seen:
                seen.add(key)
                raw.append(v)
                kept += 1
        stats[rule.name] = {
            "wall_s": _time.perf_counter() - t0,
            "found": kept,
        }
    violations: List[Violation] = []
    suppressed: List[Violation] = []
    for v in raw:
        hit = None
        for entry in allowlist:
            if entry.matches(v):
                hit = entry
                break
        if hit is not None:
            hit.used = True
            suppressed.append(v)
        else:
            violations.append(v)
    for entry in allowlist:
        skip_staleness = (
            changed_set is not None
            and entry.rule not in program_rule_names
            and entry.path not in changed_set
        )
        # the justification check needs no rescan — it must hold even
        # in incremental runs (check.sh's default), or an unjustified
        # entry would sail through the very gate it's meant to face
        if not entry.reason.strip():
            violations.append(Violation(
                rule="allowlist-justification", path="graftcheck.toml",
                line=entry.lineno, col=0, func="<allowlist>",
                symbol=entry.rule,
                message=(
                    f"allowlist entry for {entry.rule} at {entry.path} "
                    f"carries no written justification"
                ),
            ))
        if not entry.used and not skip_staleness:
            # staleness IS unknowable incrementally: this entry's rule
            # never rescanned its file, so "matches no violation" would
            # be an artifact of the narrowed scan, not a finding
            violations.append(Violation(
                rule="stale-allowlist", path="graftcheck.toml",
                line=entry.lineno, col=0, func="<allowlist>",
                symbol=entry.rule,
                message=(
                    f"allowlist entry for {entry.rule} at {entry.path} "
                    f"(func={entry.func!r}, symbol={entry.symbol!r}) "
                    f"matches no current violation — delete it"
                ),
            ))
    violations.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    for v in violations:
        if v.rule in stats:
            stats[v.rule]["violations"] = \
                stats[v.rule].get("violations", 0) + 1
    for name in stats:
        stats[name].setdefault("violations", 0)
    return violations, suppressed, stats


def run_checks(
    modules: Iterable[ModuleFile],
    rules: Sequence,
    allowlist: Sequence[AllowEntry] = (),
) -> Tuple[List[Violation], List[Violation]]:
    """Compatibility wrapper over :func:`run_checks_timed` — the
    original ``(violations, suppressed)`` pair, full scan."""
    violations, suppressed, _ = run_checks_timed(
        modules, rules, allowlist
    )
    return violations, suppressed


def render(violations: Sequence[Violation], suppressed: Sequence[Violation],
           fmt: str = "text") -> str:
    if fmt == "json":
        return json.dumps(
            {
                "violations": [v.as_dict() for v in violations],
                "suppressed": [v.as_dict() for v in suppressed],
                "violation_count": len(violations),
            },
            indent=2,
        )
    lines = [v.format() for v in violations]
    lines.append(
        f"graftcheck: {len(violations)} violation(s), "
        f"{len(suppressed)} allowlisted"
    )
    return "\n".join(lines)
