"""The whole-program substrate: module symbol tables + a call graph.

PR 7's rules were per-function, per-module — and the bugs PRs 10-13
actually fixed (a ``device_get`` buried in a helper module, a lock held
across a call that re-acquires, a donated buffer read by a live
dispatch) are whole-program properties. This module gives every rule
the same cross-module view:

- :class:`FunctionInfo` — one entry per function/method (nested defs
  included), keyed ``"path::qualname"``.
- :class:`Program` — the parsed module set, the function table, and the
  resolved call graph (``calls[caller] -> [CallSite]``).

Resolution is deliberately *under-approximate*: an edge exists only
when the callee can be named with confidence — module-level functions,
imported symbols (module-level or function-level imports),
``self.method`` within a class, constructor calls, locally-typed
instances (``x = ClassName(...)``), ``self.attr`` instances typed from
``__init__`` assignments or parameter annotations, methods whose
return statement is a bare constructor (``return ObservedJit(...)``),
and — fallback — attribute calls whose method name is defined by
exactly ONE class repo-wide and is not a generic name (``get``,
``items``, ``close``, ...). Unresolvable calls produce no edge: the
whole-program rules under-report rather than false-positive, exactly
like the local taint pass.

Stdlib-only (``ast``), like the rest of the engine.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from koordinator_tpu.analysis.graftcheck.engine import ModuleFile, attr_chain

#: attribute-call method names too generic for the unique-method
#: fallback — resolving these by name alone would invent edges
#: (queue.get vs SchedulerCache.get, file.close vs proxy.close, ...)
_GENERIC_METHODS = frozenset({
    "get", "put", "pop", "add", "set", "close", "open", "start", "stop",
    "run", "join", "wait", "send", "recv", "read", "write", "flush",
    "items", "keys", "values", "append", "extend", "insert", "remove",
    "clear", "copy", "update", "sort", "index", "count", "split",
    "strip", "format", "encode", "decode", "acquire", "release",
    "submit", "result", "cancel", "done", "poll", "kill", "terminate",
    "tick", "reset", "build", "check", "apply", "match", "matches",
    "name", "status", "snapshot", "emit", "observe", "inc", "dec",
    "solve", "schedule", "lower", "replace", "_replace", "mark",
    "register", "notify", "render", "load", "dump", "dumps", "loads",
})


def module_dotted(path: str) -> str:
    """Repo-relative posix path -> importable dotted name
    (``a/b/c.py`` -> ``a.b.c``; ``a/b/__init__.py`` -> ``a.b``)."""
    dotted = path[:-3] if path.endswith(".py") else path
    if dotted.endswith("/__init__"):
        dotted = dotted[: -len("/__init__")]
    return dotted.replace("/", ".")


@dataclasses.dataclass
class FunctionInfo:
    """One function or method in the program."""

    key: str                      # "path::qualname"
    path: str
    qualname: str                 # "Class.method" | "func" | "func.inner"
    node: ast.AST                 # FunctionDef | AsyncFunctionDef
    class_name: Optional[str]     # enclosing class, if any


@dataclasses.dataclass
class CallSite:
    """One resolved call edge occurrence."""

    callee: str                   # FunctionInfo key
    node: ast.Call
    chain: str                    # the raw dotted callee text


class _ModuleTable:
    """Per-module symbol table used during resolution."""

    def __init__(self, module: ModuleFile):
        self.module = module
        self.path = module.path
        #: name -> ("func", key) | ("class", class name)
        self.symbols: Dict[str, Tuple[str, str]] = {}
        #: class name -> {method name -> key}
        self.methods: Dict[str, Dict[str, str]] = {}
        #: class name -> base class raw names
        self.bases: Dict[str, List[str]] = {}
        #: class name -> {self attr -> class name (possibly dotted import)}
        self.attr_types: Dict[str, Dict[str, str]] = {}
        #: imported alias -> ("module", dotted) | ("symbol", dotted, name)
        self.imports: Dict[str, Tuple] = {}
        #: module-level instance name -> class name (local or imported)
        self.instances: Dict[str, str] = {}
        #: method key -> returned class name (bare-constructor returns)
        self.returns_class: Dict[str, str] = {}


class Program:
    """The parsed module universe plus its resolved call graph."""

    def __init__(self, modules: Sequence[ModuleFile]):
        self.modules: List[ModuleFile] = list(modules)
        self.by_path: Dict[str, ModuleFile] = {
            m.path: m for m in self.modules
        }
        self.by_dotted: Dict[str, ModuleFile] = {
            module_dotted(m.path): m for m in self.modules
        }
        self.functions: Dict[str, FunctionInfo] = {}
        self.calls: Dict[str, List[CallSite]] = {}
        #: method name -> class keys defining it (unique-method fallback)
        self._method_owners: Dict[str, List[Tuple[str, str]]] = {}
        self._tables: Dict[str, _ModuleTable] = {}
        #: method key -> class name its bare-constructor returns build
        self._returns_class: Dict[str, str] = {}
        for m in self.modules:
            self._tables[m.path] = self._build_table(m)
        for table in self._tables.values():
            self._returns_class.update(table.returns_class)
        # phase 1.5: type module-level/instance-attr bindings whose
        # value is a METHOD call returning a bare constructor
        # (``X = DEVICE_OBS.jit("name", jax.jit(...))`` -> ObservedJit);
        # two rounds let one inferred instance feed the next
        for _ in range(2):
            for m in self.modules:
                self._infer_call_bindings(self._tables[m.path])
        for m in self.modules:
            self._resolve_module(m)

    # -- pass 1: symbol tables -----------------------------------------------

    def _build_table(self, module: ModuleFile) -> _ModuleTable:
        table = _ModuleTable(module)
        # all imports anywhere in the file (module- AND function-level:
        # hot-path modules import helpers inside functions to defer jax
        # deps; one merged table per module is a deliberate, benign
        # over-share — names practically never collide within a file)
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split(".")[0]
                    target = alias.name if alias.asname else \
                        alias.name.split(".")[0]
                    table.imports[name] = ("module", target)
            elif isinstance(node, ast.ImportFrom):
                if node.module is None or node.level:
                    continue  # relative imports unused in this repo
                for alias in node.names:
                    name = alias.asname or alias.name
                    maybe_mod = f"{node.module}.{alias.name}"
                    if maybe_mod in self.by_dotted:
                        table.imports[name] = ("module", maybe_mod)
                    else:
                        table.imports[name] = (
                            "symbol", node.module, alias.name
                        )
        self._collect_defs(module, table, module.tree.body, [], None)
        return table

    def _collect_defs(self, module: ModuleFile, table: _ModuleTable,
                      body: List[ast.stmt], scopes: List[str],
                      class_name: Optional[str]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                qual = ".".join(scopes + [stmt.name])
                key = f"{module.path}::{qual}"
                info = FunctionInfo(
                    key=key, path=module.path, qualname=qual, node=stmt,
                    class_name=class_name,
                )
                self.functions[key] = info
                if class_name is not None and len(scopes) >= 1 \
                        and scopes[-1] == class_name:
                    table.methods.setdefault(class_name, {})[
                        stmt.name] = key
                    self._method_owners.setdefault(stmt.name, []).append(
                        (module.path, class_name)
                    )
                    if stmt.name == "__init__":
                        self._collect_attr_types(table, class_name, stmt)
                    ret = self._bare_constructor_return(stmt)
                    if ret is not None:
                        table.returns_class[key] = ret
                elif not scopes:
                    table.symbols[stmt.name] = ("func", key)
                self._collect_defs(
                    module, table, stmt.body, scopes + [stmt.name],
                    class_name,
                )
            elif isinstance(stmt, ast.ClassDef):
                if not scopes:
                    table.symbols[stmt.name] = ("class", stmt.name)
                    table.bases[stmt.name] = [
                        attr_chain(b) or "" for b in stmt.bases
                    ]
                self._collect_defs(
                    module, table, stmt.body, scopes + [stmt.name],
                    stmt.name if not scopes else class_name,
                )
            elif isinstance(stmt, ast.Assign) and not scopes:
                cls = self._constructed_class(stmt.value)
                if cls is not None:
                    for t in stmt.targets:
                        if isinstance(t, ast.Name):
                            table.instances[t.id] = cls
            elif isinstance(stmt, (ast.If, ast.Try)) and not scopes:
                # module-level guards (capability gates) still define
                self._collect_defs(module, table, stmt.body, scopes,
                                   class_name)
                for extra in getattr(stmt, "orelse", []) or []:
                    self._collect_defs(module, table, [extra], scopes,
                                       class_name)

    @staticmethod
    def _constructed_class(value: ast.AST) -> Optional[str]:
        """``ClassName(...)`` (CamelCase heuristic) -> "ClassName";
        ``obj.method(...)`` whose method returns a bare constructor is
        resolved later, during the edge pass."""
        if isinstance(value, ast.Call):
            chain = attr_chain(value.func)
            if chain is not None:
                seg = chain.split(".")[-1]
                if seg[:1].isupper():
                    return seg
        return None

    def _collect_attr_types(self, table: _ModuleTable, class_name: str,
                            init: ast.FunctionDef) -> None:
        """``self.attr`` instance types from ``__init__``: direct
        constructor assignments and parameter pass-throughs whose
        parameter carries a class annotation (``Optional[T]``
        included)."""
        ann: Dict[str, str] = {}
        args = init.args
        for a in list(args.args) + list(args.kwonlyargs):
            if a.annotation is not None:
                cls = _annotation_class(a.annotation)
                if cls is not None:
                    ann[a.arg] = cls
        out = table.attr_types.setdefault(class_name, {})
        for stmt in ast.walk(init):
            if not isinstance(stmt, ast.Assign) or len(stmt.targets) != 1:
                continue
            t = stmt.targets[0]
            if not (isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"):
                continue
            cls = self._constructed_class(stmt.value)
            if cls is None and isinstance(stmt.value, ast.Name):
                cls = ann.get(stmt.value.id)
            if cls is not None:
                out.setdefault(t.attr, cls)

    @staticmethod
    def _bare_constructor_return(fn: ast.AST) -> Optional[str]:
        """A method whose only returns are ``return ClassName(...)``
        types its callers' bindings (``DEVICE_OBS.jit`` ->
        ``ObservedJit``)."""
        found: Optional[str] = None
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                cls = Program._constructed_class(node.value)
                if cls is None:
                    return None
                if found is not None and found != cls:
                    return None
                found = cls
        return found

    # -- pass 1.5: call-return instance typing -------------------------------

    def _call_return_class(self, table: _ModuleTable, call: ast.Call
                           ) -> Optional[str]:
        """The class a call provably constructs: a constructor call, or
        a method whose returns are all one bare constructor."""
        site = self._resolve_call(table, call, None,
                                  dict(table.instances))
        if site is None:
            return None
        if site.callee.endswith(".__init__"):
            return site.callee.rsplit("::", 1)[1].split(".")[0]
        return self._returns_class.get(site.callee)

    def _infer_call_bindings(self, table: _ModuleTable) -> None:
        tree = table.module.tree
        for stmt in ast.walk(tree):
            if isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.value, ast.Call):
                t = stmt.targets[0]
                cls = None
                if isinstance(t, ast.Name):
                    if t.id not in table.instances:
                        cls = self._call_return_class(table, stmt.value)
                        if cls is not None:
                            table.instances[t.id] = cls
                elif isinstance(t, ast.Attribute) \
                        and isinstance(t.value, ast.Name) \
                        and t.value.id == "self":
                    # which class this `self` belongs to: the enclosing
                    # ClassDef (found via a parent scan per class)
                    owner = self._enclosing_class(tree, stmt)
                    if owner is not None and t.attr not in \
                            table.attr_types.get(owner, {}):
                        cls = self._call_return_class(table, stmt.value)
                        if cls is not None:
                            table.attr_types.setdefault(
                                owner, {})[t.attr] = cls

    @staticmethod
    def _enclosing_class(tree: ast.Module, target: ast.stmt
                         ) -> Optional[str]:
        for node in ast.walk(tree):
            if isinstance(node, ast.ClassDef):
                for sub in ast.walk(node):
                    if sub is target:
                        return node.name
        return None

    # -- pass 2: call-edge resolution ----------------------------------------

    def _resolve_class(self, table: _ModuleTable, name: str
                       ) -> Optional[Tuple[_ModuleTable, str]]:
        """A raw class name in ``table``'s module -> (owning table,
        class name)."""
        sym = table.symbols.get(name)
        if sym is not None and sym[0] == "class":
            return table, sym[1]
        imp = table.imports.get(name)
        if imp is not None and imp[0] == "symbol":
            target = self.by_dotted.get(imp[1])
            if target is not None:
                t2 = self._tables[target.path]
                sym2 = t2.symbols.get(imp[2])
                if sym2 is not None and sym2[0] == "class":
                    return t2, sym2[1]
        return None

    def _method_key(self, table: _ModuleTable, class_name: str,
                    method: str, _depth: int = 0
                    ) -> Optional[str]:
        """Resolve ``class.method`` in ``table``'s module, walking
        resolvable base classes."""
        key = table.methods.get(class_name, {}).get(method)
        if key is not None:
            return key
        if _depth >= 4:
            return None
        for base in table.bases.get(class_name, []):
            resolved = self._resolve_class(table, base.split(".")[-1])
            if resolved is not None:
                bt, bname = resolved
                key = self._method_key(bt, bname, method, _depth + 1)
                if key is not None:
                    return key
        return None

    def _unique_method(self, method: str) -> Optional[Tuple[str, str]]:
        if method in _GENERIC_METHODS or method.startswith("__"):
            return None
        owners = self._method_owners.get(method, [])
        if len(owners) == 1:
            return owners[0]
        return None

    def _resolve_module_table(self, dotted: str) -> Optional[_ModuleTable]:
        mod = self.by_dotted.get(dotted)
        return self._tables[mod.path] if mod is not None else None

    def _resolve_module(self, module: ModuleFile) -> None:
        table = self._tables[module.path]
        self._resolve_body(
            table, module.tree.body, [], None, dict(table.instances)
        )

    def _resolve_body(self, table: _ModuleTable, body: List[ast.stmt],
                      scopes: List[str], class_name: Optional[str],
                      local_types: Dict[str, str]) -> None:
        """Walk one scope level: collect this scope's call edges and
        recurse into nested defs with fresh local type maps. Compound
        statements (``with``/``if``/``for``/``try``) are walked as
        statement lists so local instance typing survives into their
        bodies — the hot classes do nearly everything under ``with
        self._lock:``."""
        caller = ".".join(scopes) if scopes else "<module>"
        caller_key = f"{table.path}::{caller}"

        def emit_calls(expr: Optional[ast.AST]) -> None:
            """Resolve every Call in an expression tree, pruned at
            nested function defs (their own scope pass owns those);
            lambda bodies stay attributed to this caller."""
            if expr is None:
                return
            stack = [expr]
            while stack:
                node = stack.pop()
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    continue
                if isinstance(node, ast.Call):
                    site = self._resolve_call(
                        table, node, class_name, local_types
                    )
                    if site is not None:
                        self.calls.setdefault(
                            caller_key, []).append(site)
                stack.extend(ast.iter_child_nodes(node))

        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in stmt.decorator_list:
                    emit_calls(dec)
                self._resolve_body(
                    table, stmt.body, scopes + [stmt.name], class_name,
                    dict(local_types),
                )
                # defining a nested function gives the parent a
                # may-invoke edge (closures run on the parent's behalf)
                nested_key = (
                    f"{table.path}::{'.'.join(scopes + [stmt.name])}"
                )
                if scopes and nested_key in self.functions:
                    self.calls.setdefault(caller_key, []).append(CallSite(
                        callee=nested_key, node=None, chain=stmt.name,
                    ))
            elif isinstance(stmt, ast.ClassDef):
                for dec in stmt.decorator_list:
                    emit_calls(dec)
                self._resolve_body(
                    table, stmt.body, scopes + [stmt.name],
                    stmt.name if class_name is None else class_name,
                    dict(local_types),
                )
            elif isinstance(stmt, ast.Assign):
                # local instance typing: x = ClassName(...) / x = self.attr
                if len(stmt.targets) == 1 \
                        and isinstance(stmt.targets[0], ast.Name):
                    name = stmt.targets[0].id
                    cls = self._constructed_class(stmt.value)
                    if cls is None \
                            and isinstance(stmt.value, ast.Attribute) \
                            and isinstance(stmt.value.value, ast.Name) \
                            and stmt.value.value.id == "self" \
                            and class_name is not None:
                        cls = table.attr_types.get(class_name, {}).get(
                            stmt.value.attr
                        )
                    if cls is not None:
                        local_types[name] = cls
                emit_calls(stmt.value)
                for t in stmt.targets:
                    emit_calls(t)
            elif isinstance(stmt, (ast.If, ast.While)):
                emit_calls(stmt.test)
                self._resolve_body(table, stmt.body, scopes, class_name,
                                   local_types)
                self._resolve_body(table, stmt.orelse, scopes,
                                   class_name, local_types)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                emit_calls(stmt.iter)
                emit_calls(stmt.target)
                self._resolve_body(table, stmt.body, scopes, class_name,
                                   local_types)
                self._resolve_body(table, stmt.orelse, scopes,
                                   class_name, local_types)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    emit_calls(item.context_expr)
                self._resolve_body(table, stmt.body, scopes, class_name,
                                   local_types)
            elif isinstance(stmt, ast.Try):
                for block in (stmt.body, stmt.orelse, stmt.finalbody):
                    self._resolve_body(table, block, scopes, class_name,
                                       local_types)
                for handler in stmt.handlers:
                    self._resolve_body(table, handler.body, scopes,
                                       class_name, local_types)
            elif isinstance(stmt, ast.Match):
                emit_calls(stmt.subject)
                for case in stmt.cases:
                    emit_calls(case.guard)
                    self._resolve_body(table, case.body, scopes,
                                       class_name, local_types)
            else:
                for child in ast.iter_child_nodes(stmt):
                    emit_calls(child)

    def _resolve_call(self, table: _ModuleTable, call: ast.Call,
                      class_name: Optional[str],
                      local_types: Dict[str, str]) -> Optional[CallSite]:
        func = call.func
        chain = attr_chain(func) or ""
        if isinstance(func, ast.Name):
            return self._resolve_name_call(table, call, func.id, chain)
        if not isinstance(func, ast.Attribute):
            return None
        method = func.attr
        base = func.value
        # self.method(...)
        if isinstance(base, ast.Name) and base.id == "self" \
                and class_name is not None:
            key = self._method_key(table, class_name, method)
            if key is not None:
                return CallSite(callee=key, node=call, chain=chain)
            return None
        base_chain = attr_chain(base)
        owner = None  # (table, class) of the receiver instance
        if isinstance(base, ast.Name):
            # imported module alias: mod.func(...)
            imp = table.imports.get(base.id)
            if imp is not None and imp[0] == "module":
                t2 = self._resolve_module_table(imp[1])
                if t2 is not None:
                    return self._resolve_symbol_call(
                        t2, call, method, chain
                    )
            # local / module-level instance, or a class name
            cls = local_types.get(base.id) or table.instances.get(base.id)
            if cls is None:
                resolved = self._resolve_class(table, base.id)
                if resolved is not None:
                    owner = resolved
            else:
                owner = self._owner_for_class(table, cls)
            if owner is None and cls is None and imp is not None \
                    and imp[0] == "symbol":
                # imported NAME that is a module-level instance there
                t2 = self._resolve_module_table(imp[1])
                if t2 is not None:
                    cls2 = t2.instances.get(imp[2])
                    if cls2 is not None:
                        owner = self._owner_for_class(t2, cls2)
        elif base_chain is not None and base_chain.startswith("self.") \
                and base_chain.count(".") == 1 and class_name is not None:
            attr = base_chain.split(".")[1]
            cls = table.attr_types.get(class_name, {}).get(attr)
            if cls is not None:
                owner = self._owner_for_class(table, cls)
        if owner is not None:
            t2, cls_name = owner
            key = self._method_key(t2, cls_name, method)
            if key is not None:
                return CallSite(callee=key, node=call, chain=chain)
            return None
        # unique-method fallback (distinctive names only)
        unique = self._unique_method(method)
        if unique is not None:
            path, cls_name = unique
            t2 = self._tables[path]
            key = t2.methods.get(cls_name, {}).get(method)
            if key is not None:
                return CallSite(callee=key, node=call, chain=chain)
        return None

    def _owner_for_class(self, table: _ModuleTable, cls: str
                         ) -> Optional[Tuple[_ModuleTable, str]]:
        resolved = self._resolve_class(table, cls.split(".")[-1])
        if resolved is not None:
            return resolved
        # class defined in SOME module, unique by name
        owners = [
            (p, c) for p, t in self._tables.items()
            for c in t.methods if c == cls.split(".")[-1]
        ]
        if len(owners) == 1:
            p, c = owners[0]
            return self._tables[p], c
        return None

    def _resolve_name_call(self, table: _ModuleTable, call: ast.Call,
                           name: str, chain: str) -> Optional[CallSite]:
        sym = table.symbols.get(name)
        if sym is not None:
            if sym[0] == "func":
                return CallSite(callee=sym[1], node=call, chain=chain)
            key = self._method_key(table, sym[1], "__init__")
            if key is not None:
                return CallSite(callee=key, node=call, chain=chain)
            return None
        imp = table.imports.get(name)
        if imp is not None and imp[0] == "symbol":
            t2 = self._resolve_module_table(imp[1])
            if t2 is not None:
                return self._resolve_symbol_call(t2, call, imp[2], chain)
        return None

    def _resolve_symbol_call(self, table: _ModuleTable, call: ast.Call,
                             name: str, chain: str) -> Optional[CallSite]:
        sym = table.symbols.get(name)
        if sym is not None:
            if sym[0] == "func":
                return CallSite(callee=sym[1], node=call, chain=chain)
            key = self._method_key(table, sym[1], "__init__")
            if key is not None:
                return CallSite(callee=key, node=call, chain=chain)
            return None
        # a module-level instance: calling it dispatches to __call__;
        # its methods resolve through the instance's class
        cls = table.instances.get(name)
        if cls is not None:
            resolved = self._owner_for_class(table, cls)
            if resolved is not None:
                t2, cls_name = resolved
                key = self._method_key(t2, cls_name, "__call__")
                if key is not None:
                    return CallSite(callee=key, node=call, chain=chain)
        return None

    # -- queries -------------------------------------------------------------

    def callees(self, key: str) -> List[CallSite]:
        return self.calls.get(key, [])

    def module_table(self, path: str) -> Optional[_ModuleTable]:
        return self._tables.get(path)

    def instance_class(self, path: str, name: str) -> Optional[str]:
        """Module-level instance name -> class name (for rule configs
        that reference singletons)."""
        t = self._tables.get(path)
        return t.instances.get(name) if t is not None else None

    def attr_type(self, path: str, class_name: str, attr: str
                  ) -> Optional[str]:
        t = self._tables.get(path)
        if t is None:
            return None
        return t.attr_types.get(class_name, {}).get(attr)


def _annotation_class(ann: ast.AST) -> Optional[str]:
    """``T`` / ``Optional[T]`` / ``"T"`` annotation -> class name."""
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        name = ann.value.split(".")[-1].strip()
        return name if name[:1].isupper() else None
    if isinstance(ann, ast.Subscript):
        head = attr_chain(ann.value) or ""
        if head.split(".")[-1] in ("Optional", "Union"):
            inner = ann.slice
            if isinstance(inner, ast.Tuple):
                cands = [
                    _annotation_class(e) for e in inner.elts
                    if not (isinstance(e, ast.Constant)
                            and e.value is None)
                ]
                cands = [c for c in cands if c is not None]
                return cands[0] if len(cands) == 1 else None
            return _annotation_class(inner)
        return None
    chain = attr_chain(ann)
    if chain is not None:
        name = chain.split(".")[-1]
        return name if name[:1].isupper() else None
    return None


def build_program(modules: Sequence[ModuleFile]) -> Program:
    return Program(modules)
