"""graftcheck: AST invariant checker for the solve hot path.

PR 6 made steady-state scheduling ticks incremental and device-resident.
Every invariant that perf win rests on is structural, not local — a
single ``float(device_value)`` inside the solve loop, one cache
attribute touched off-lock, one inline row computation that drifts from
the shared per-row helpers, or one jit callsite without explicit
static/donate declarations silently costs correctness or a recompile
per tick. Convention and code review don't scale to that; this package
machine-checks them, the way the reference Koordinator leans on Go's
race detector and ``go vet``.

Local rules (each self-tested against seeded-violation fixtures in
``tests/fixtures/graftcheck/``; see docs/DESIGN.md §11):

- ``host-sync``      no host synchronization on device values inside
                     hot-path modules (local taint analysis).
- ``lock-discipline`` mapped mutable attributes of the concurrency-
                     critical classes only touched under their lock.
- ``delta-parity``   the full and delta lowerings reach row values only
                     through the shared per-row helper registry.
- ``jit-hygiene``    every ``jax.jit``/``pjit`` in hot-path modules
                     declares static/donate intent explicitly; jitted
                     callables never fed per-call-varying Python scalars.
- ``dead-import``    no unused imports in hot-path modules.

Whole-program rules (ISSUE 9; a resolved cross-module call graph,
``callgraph.Program`` — docs/DESIGN.md §18):

- ``sync-reach``     interprocedural host-sync taint: a ``device_get``
                     buried N calls below a hot-path function is caught
                     in any module, scoped or not.
- ``lock-order``     the mapped locks' acquisition graph (nested-with +
                     call-under-lock edges) must be acyclic; a cycle is
                     a potential deadlock. Runtime twin:
                     ``koordinator_tpu/testing/lockorder.py``.
- ``donation-safety`` anything passed to a ``donate_argnums`` jit must
                     be provably dead afterwards — no later read, no
                     donation of a possibly-pinned staged generation.
- ``determinism-taint`` wall clock, unseeded RNGs, and set iteration
                     order never flow into device values or wire frames
                     (the oracle bit-parity inputs).

Intentional exceptions live in ``graftcheck.toml`` at the repo root;
every entry must carry a written justification and match at least one
current violation (stale entries are themselves violations).

CLI: ``python -m koordinator_tpu.analysis.graftcheck [--format=json]
[--rule=NAME ...] [--changed-files=PATHS|auto]`` — exits non-zero on
any unsuppressed violation; JSON output carries per-rule wall time and
violation counts. ``--changed-files`` scans only the named files with
the local rules while the whole-program passes always analyze the full
call graph.
"""

from koordinator_tpu.analysis.graftcheck.engine import (
    AllowEntry,
    ModuleFile,
    Violation,
    load_allowlist,
    load_module,
    run_checks,
    run_checks_timed,
)
from koordinator_tpu.analysis.graftcheck.rules import default_rules

__all__ = [
    "AllowEntry",
    "ModuleFile",
    "Violation",
    "default_rules",
    "load_allowlist",
    "load_module",
    "run_checks",
    "run_checks_timed",
]
