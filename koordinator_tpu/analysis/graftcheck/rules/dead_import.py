"""dead-import: no unused imports in hot-path modules.

A dead import in the hot path is latency (module import cost, often a
jax/numpy transitive tree) and a lie to the reader about the module's
dependency surface. The used-name set is over-approximated — any
identifier appearing anywhere in the module, plus identifier-shaped
words inside string constants (string annotations under ``from
__future__ import annotations``) — so the rule under-reports rather
than false-positives. ``__init__.py`` files are skipped (imports there
are re-exports).
"""

from __future__ import annotations

import ast
import re
from typing import List, Sequence, Set

from koordinator_tpu.analysis.graftcheck.engine import ModuleFile, Violation

_WORD = re.compile(r"[A-Za-z_][A-Za-z0-9_]*")


class DeadImportRule:
    name = "dead-import"
    description = "imported names must be used somewhere in the module"

    def __init__(self, scope: Sequence[str]):
        self.scope = tuple(scope)

    def check(self, module: ModuleFile) -> List[Violation]:
        if not module.matches(self.scope):
            return []
        if module.path.endswith("__init__.py"):
            return []
        imports = []  # (bound name, node, shown name)
        used: Set[str] = set()
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    bound = alias.asname or alias.name.split(".")[0]
                    imports.append((bound, node, alias.name))
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    bound = alias.asname or alias.name
                    imports.append((bound, node, alias.name))
            elif isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                used.add(node.attr)
            elif isinstance(node, ast.Constant) and isinstance(
                node.value, str
            ):
                used.update(_WORD.findall(node.value))
        out: List[Violation] = []
        seen_bound: Set[str] = set()
        for bound, node, shown in imports:
            # an import statement binding also "uses" its own name once;
            # Name nodes never cover import bindings, so no exclusion
            # bookkeeping is needed — but a name imported twice only
            # reports once
            if bound in used or bound in seen_bound:
                continue
            seen_bound.add(bound)
            out.append(Violation(
                rule=self.name, path=module.path, line=node.lineno,
                col=node.col_offset, func="<module>", symbol=bound,
                message=f"import {shown!r} (bound as {bound!r}) is unused",
            ))
        return out
