"""The graftcheck rule set and its production configuration.

``default_rules()`` returns every rule wired to the repo's hot-path
scope and invariant registries; tests construct the same rule classes
with narrowed scopes/registries to self-test against seeded-violation
fixtures.
"""

from __future__ import annotations

from koordinator_tpu.analysis.graftcheck.rules.dead_import import (
    DeadImportRule,
)
from koordinator_tpu.analysis.graftcheck.rules.determinism import (
    DeterminismRule,
)
from koordinator_tpu.analysis.graftcheck.rules.donation import (
    DonationRule,
    PinSpec,
)
from koordinator_tpu.analysis.graftcheck.rules.host_sync import HostSyncRule
from koordinator_tpu.analysis.graftcheck.rules.jit_hygiene import (
    JitHygieneRule,
)
from koordinator_tpu.analysis.graftcheck.rules.lock_discipline import (
    LockDisciplineRule,
    LockSpec,
)
from koordinator_tpu.analysis.graftcheck.rules.lock_order import (
    LockNode,
    LockOrderRule,
)
from koordinator_tpu.analysis.graftcheck.rules.parity import (
    DeltaParityRule,
    ParitySpec,
)
from koordinator_tpu.analysis.graftcheck.rules.sync_reach import (
    SyncReachRule,
)

#: the solve hot path: modules where a stray host sync, implicit jit
#: declaration, or dead import is a per-tick cost, not a style nit
HOT_MODULES = (
    "koordinator_tpu/models/placement.py",
    "koordinator_tpu/ops/*.py",
    "koordinator_tpu/state/cluster.py",
    "koordinator_tpu/service/server.py",
    "koordinator_tpu/service/admission.py",
    # the multi-tenant pool (DESIGN §20): its lane staging + dispatch
    # run on the gate's executor thread — the serving hot path
    "koordinator_tpu/service/tenancy.py",
    "koordinator_tpu/service/failover.py",
    # the AOT warm pool (DESIGN §21): serve() sits on every adopted
    # solve call — a stray sync or implicit jit there is per-tick cost
    "koordinator_tpu/service/warmpool.py",
    "koordinator_tpu/parallel/mesh.py",
    # the auditor runs between scheduling rounds, not in the solve loop,
    # but it handles staged device values: its ONE intentional read-back
    # (the parity probe) is allowlisted by name; anything else is a bug
    "koordinator_tpu/scheduler/auditor.py",
    # the pipelined tick path: the coordinator half (submit/prestage)
    # must stay taint-clean — the solve's read-back belongs to exactly
    # one publish-side site (InFlightSchedule.finalize); a stray sync
    # here would put the device compute back on the round's critical
    # path
    "koordinator_tpu/scheduler/pipeline.py",
    # the trace fabric: span emission rides inside every hot module
    # above, so the obs layer itself must be provably taint-clean — its
    # ONE intentional read-back (the explain breakdown's host
    # materialization, obs/explain.py) is allowlisted by name; any
    # other device sync here would hide a per-tick stall inside
    # "observability"
    "koordinator_tpu/obs/*.py",
)

#: attribute -> lock maps for the concurrency-critical classes the
#: incremental staging path relies on (docs/DESIGN.md §11)
LOCK_SPECS = (
    LockSpec(
        path="koordinator_tpu/scheduler/cache.py",
        class_name="SchedulerCache",
        lock="_lock",
        attrs=(
            "nodes", "pods", "pending", "assumed", "node_metrics",
            "gangs", "quotas", "reservations",
        ),
    ),
    LockSpec(
        path="koordinator_tpu/state/cluster.py",
        class_name="ClusterDeltaTracker",
        lock="_lock",
        attrs=("epoch", "structure_epoch", "_marks"),
    ),
    LockSpec(
        path="koordinator_tpu/models/placement.py",
        class_name="StagedStateCache",
        lock="_lock",
        attrs=(
            "arrays", "state", "tracker", "seen_epoch", "epoch",
            "last_delta", "last_path", "last_now", "_pinned",
            "_wire_delta",
        ),
    ),
    # the pipelined tick loop's state machine: the coordinator thread
    # (submit/drain/status) and the publisher worker (retire) share it
    LockSpec(
        path="koordinator_tpu/scheduler/pipeline.py",
        class_name="TickPipeline",
        lock="_lock",
        attrs=(
            "_inflight", "_pending_error", "_rounds", "_last",
            "_stopped",
        ),
    ),
    # the anti-entropy auditor: sweeps run on the scheduling-loop
    # thread, status() is read from debug-mux handler threads
    LockSpec(
        path="koordinator_tpu/scheduler/auditor.py",
        class_name="StateAuditor",
        lock="_lock",
        attrs=(
            "_promotion_pending", "_rounds_since", "_probe_cursor",
            "_unrepairable", "sweeps", "detections", "repairs",
            "last_report",
        ),
    ),
    LockSpec(
        path="koordinator_tpu/service/admission.py",
        class_name="AdmissionGate",
        lock="_lock",
        attrs=("_lanes", "_closed", "_stats", "_undelivered",
               "_tenant_stats"),
    ),
    # the multi-tenant pool's weight registry (DESIGN §20): read on the
    # gate's submit/claim paths (under the gate lock — a documented
    # gate→registry order edge), written by operators/tests
    LockSpec(
        path="koordinator_tpu/service/tenancy.py",
        class_name="TenantRegistry",
        lock="_lock",
        attrs=("_weights",),
    ),
    # the AOT warm pool (docs/DESIGN.md §21): adopted solve calls
    # serve() under it, the background persister and promotion
    # restores mutate it, debug muxes read status(). ``serving`` is a
    # plain fast-path flag read without the lock (same contract as
    # DeviceObservatory.enabled); everything else is mapped. The pool
    # lock never nests with any other mapped lock (compiles and disk
    # I/O always run outside it).
    LockSpec(
        path="koordinator_tpu/service/warmpool.py",
        class_name="WarmPool",
        lock="_lock",
        attrs=(
            "_cache", "_configured", "_single_device", "_reg", "_execs",
            "_persisted",
            "_manifest", "hits", "misses", "rejects", "quarantined",
            "served",
            "load_s_total", "compiles", "last_restore", "last_error",
            "_bg_thread", "_bg_stop", "_restore_thread",
        ),
    ),
    # the failover state machine: scheduler ticks, recovery probes, and
    # status() readers all cross it (docs/DESIGN.md §13)
    LockSpec(
        path="koordinator_tpu/service/failover.py",
        class_name="FailoverSolver",
        lock="_lock",
        attrs=(
            "degraded", "degraded_since", "consecutive_failures",
            "healthy_probes", "flips_to_degraded", "flips_to_remote",
            "local_solves", "last_error", "last_mode",
        ),
    ),
    # the supervisor: the monitor thread, start()/stop() callers, and
    # status() readers share the child handle and restart bookkeeping
    LockSpec(
        path="koordinator_tpu/service/supervisor.py",
        class_name="SolverSupervisor",
        lock="_lock",
        attrs=(
            "_proc", "state", "restarts_total",
            "consecutive_probe_failures", "last_exit_code",
            "_backoff_attempt", "_spawned_at", "_ready_since_spawn",
            "_respawn_warm", "respawns_warm_total", "_warm_probe_at",
        ),
    ),
    # the trace fabric (docs/DESIGN.md §16): every thread in the
    # process — coordinator, publisher, gate executor, sidecar
    # handlers, debug-mux readers — appends into one ring
    LockSpec(
        path="koordinator_tpu/obs/trace.py",
        class_name="SpanTracer",
        lock="_lock",
        attrs=("_events", "_open", "_stuck", "_round", "_next_span",
               "_emitted"),
    ),
    # per-pod timelines: informer intake, the tick path, and the
    # publish side all stamp stages; debug-mux readers snapshot
    LockSpec(
        path="koordinator_tpu/obs/timeline.py",
        class_name="PodTimelines",
        lock="_lock",
        attrs=("_active", "_completed", "_dropped", "_on_drop"),
    ),
    # the streaming intake (docs/DESIGN.md §22): submitter threads
    # admit, the loop thread takes rounds, the pipeline's publisher
    # resolves outcomes, debug-mux readers snapshot — one condition
    # guards it all (shared with the loop's trigger wait)
    LockSpec(
        path="koordinator_tpu/scheduler/streaming.py",
        class_name="ArrivalGate",
        lock="_lock",
        attrs=("_lanes", "_by_uid", "_inflight", "_waiting",
               "_resolved", "_resolved_map", "_stats"),
    ),
    # the streaming loop's own bookkeeping (round counters, the
    # replayable round log): loop thread writes, status() readers and
    # the publisher-thread round resolution cross it
    LockSpec(
        path="koordinator_tpu/scheduler/streaming.py",
        class_name="StreamingLoop",
        lock="_lock",
        attrs=("_rounds", "_skipped", "_last_trigger",
               "_last_fired_at", "round_log"),
    ),
    # the flight recorder: tick paths record, anomaly paths trigger
    # (possibly from other threads), the mux reads dumps
    LockSpec(
        path="koordinator_tpu/obs/flight.py",
        class_name="FlightRecorder",
        lock="_lock",
        attrs=("_ring", "_dumps", "_last_dump", "_dump_dir",
               "_min_interval_s", "_seq", "_files", "_max_files"),
    ),
    # the device-cost observatory (docs/DESIGN.md §17): instrumented
    # jit calls record from solve threads, the monitoring listener
    # fires from whichever thread compiles, analyze()/status() run from
    # debug-mux handlers and bench harnesses. ``enabled`` and
    # ``_profile_hot`` are plain fast-path flags read without the lock
    # (same contract as SpanTracer.enabled); everything else is mapped.
    LockSpec(
        path="koordinator_tpu/obs/device.py",
        class_name="DeviceObservatory",
        lock="_lock",
        attrs=(
            "_seen", "_fn_cache_sizes", "_ring", "_pending", "_analyses",
            "_analysis_order",
            "_padding", "_owners", "_seq", "_compiles_total",
            "_xla_compiles", "_xla_compile_s", "_profile_dir",
            "_profile_min_interval_s", "_profile_max_windows",
            "_profile_armed", "_profile_remaining", "_profile_path",
            "_profile_last_at", "_profile_windows", "_profile_error",
        ),
    ),
)

#: the delta/full lowering pair and the shared per-row helper registry
#: both paths must route row values through
PARITY_SPECS = (
    # lower_node_rows — the auditor's parity-probe lowering — is held
    # to the same registry as the full/delta pair: a probe that
    # computed rows its own way could cry drift (or miss it) purely
    # from divergent arithmetic
    ParitySpec(
        path="koordinator_tpu/state/cluster.py",
        funcs=("lower_nodes", "lower_nodes_delta", "lower_node_rows"),
        required_helpers=(
            "_node_metric_row", "_node_hold_rows", "_clip_i32",
            "resources_to_vector",
        ),
        allowed_helpers=("_metric_fresh",),
    ),
    # the sharded staging row path (ISSUE 10): pad_node_rows builds the
    # inert padding rows every node-sharded device_put appends — held
    # to the same registry discipline as the lowering pair, so a
    # padding row is always "a permanently empty node" built by the
    # shared helpers and never an inline per-caller fold that could
    # drift from what an unschedulable zero node lowers to
    ParitySpec(
        path="koordinator_tpu/state/cluster.py",
        funcs=("pad_node_rows",),
        required_helpers=("_pad_width", "_pad_axis0", "_pad_names"),
    ),
)


#: every mapped lock as a node of the whole-program lock-order graph:
#: the twelve LockSpec classes' primary locks plus the observatory's
#: documented secondary lock (``_profile_io_lock`` OUTER, ``_lock``
#: inner — obs/device.py) so the documented order is machine-checked
#: RLock-backed classes: same-instance re-acquisition is legal, so the
#: static pass suppresses their self-edges (scheduler/cache.py:42,
#: scheduler/auditor.py:109)
_REENTRANT_CLASSES = frozenset({"SchedulerCache", "StateAuditor"})

LOCK_NODES = tuple(
    LockNode(path=spec.path, class_name=spec.class_name, lock=spec.lock,
             reentrant=spec.class_name in _REENTRANT_CLASSES)
    for spec in LOCK_SPECS
) + (
    LockNode(path="koordinator_tpu/obs/device.py",
             class_name="DeviceObservatory", lock="_profile_io_lock"),
)

#: pin protocols the donation-safety rule enforces: the staged device
#: generation may only be donated when provably not held by an
#: in-flight solve (the PR 11 scatter-clobber invariant)
PIN_SPECS = (
    PinSpec(
        path="koordinator_tpu/models/placement.py",
        class_name="StagedStateCache",
        attr="state",
        pin_attr="_pinned",
    ),
)

#: the warm path never donates (DESIGN §19.2 / §21): every jit factory
#: in these modules must declare donate_argnums=() — the warm pool
#: stores and replays serialized executables, and a donated program
#: replayed from a persistent store mis-applies its alias map on this
#: jax line. The companion adopt-site check (DonationRule) additionally
#: refuses donating bindings at every WARM_POOL.adopt call repo-wide.
NO_DONATE_MODULES = (
    "koordinator_tpu/service/warmpool.py",
)

#: determinism-taint scope: the hot modules plus the wire codec and its
#: client/server callers — everything whose outputs the oracle parity
#: and chaos bit-identity tests compare
DETERMINISM_MODULES = HOT_MODULES + (
    "koordinator_tpu/service/codec.py",
    "koordinator_tpu/service/client.py",
)


def default_rules():
    return (
        HostSyncRule(scope=HOT_MODULES),
        LockDisciplineRule(specs=LOCK_SPECS),
        DeltaParityRule(specs=PARITY_SPECS),
        JitHygieneRule(scope=HOT_MODULES),
        DeadImportRule(scope=HOT_MODULES),
        # whole-program passes (ISSUE 9): cross-module sync taint, the
        # lock acquisition order, donation liveness, determinism taint
        SyncReachRule(scope=HOT_MODULES),
        LockOrderRule(locks=LOCK_NODES),
        DonationRule(pin_specs=PIN_SPECS,
                     no_donate_globs=NO_DONATE_MODULES),
        DeterminismRule(scope=DETERMINISM_MODULES),
    )


__all__ = [
    "DETERMINISM_MODULES",
    "HOT_MODULES",
    "LOCK_NODES",
    "LOCK_SPECS",
    "NO_DONATE_MODULES",
    "PARITY_SPECS",
    "PIN_SPECS",
    "DeadImportRule",
    "DeltaParityRule",
    "DeterminismRule",
    "DonationRule",
    "HostSyncRule",
    "JitHygieneRule",
    "LockDisciplineRule",
    "LockNode",
    "LockOrderRule",
    "LockSpec",
    "ParitySpec",
    "PinSpec",
    "SyncReachRule",
    "default_rules",
]
