"""The graftcheck rule set and its production configuration.

``default_rules()`` returns every rule wired to the repo's hot-path
scope and invariant registries; tests construct the same rule classes
with narrowed scopes/registries to self-test against seeded-violation
fixtures.
"""

from __future__ import annotations

from koordinator_tpu.analysis.graftcheck.rules.dead_import import (
    DeadImportRule,
)
from koordinator_tpu.analysis.graftcheck.rules.determinism import (
    DeterminismRule,
)
from koordinator_tpu.analysis.graftcheck.rules.donation import (
    DonationRule,
    PinSpec,
)
from koordinator_tpu.analysis.graftcheck.rules.host_sync import HostSyncRule
from koordinator_tpu.analysis.graftcheck.rules.jit_hygiene import (
    JitHygieneRule,
)
from koordinator_tpu.analysis.graftcheck.rules.lock_discipline import (
    LockDisciplineRule,
    LockSpec,
)
from koordinator_tpu.analysis.graftcheck.rules.lock_order import (
    LockNode,
    LockOrderRule,
)
from koordinator_tpu.analysis.graftcheck.rules.parity import (
    DeltaParityRule,
    ParitySpec,
)
from koordinator_tpu.analysis.graftcheck.rules.metrics_hygiene import (
    LabelDomain,
    MetricsHygieneRule,
    MetricsSpec,
)
from koordinator_tpu.analysis.graftcheck.rules.shape_flow import (
    AxisSpec,
    BindingSpec,
    BucketFlowRule,
    SignatureSpaceRule,
    WarmCoverageRule,
)
from koordinator_tpu.analysis.graftcheck.rules.sync_reach import (
    SyncReachRule,
)
from koordinator_tpu.analysis.graftcheck.shapeflow import BucketFn

#: the solve hot path: modules where a stray host sync, implicit jit
#: declaration, or dead import is a per-tick cost, not a style nit
HOT_MODULES = (
    "koordinator_tpu/models/placement.py",
    "koordinator_tpu/ops/*.py",
    "koordinator_tpu/state/cluster.py",
    # the HBM working-set manager (DESIGN §26): touch/admit/enforce run
    # inside every staging and scatter — pure ledger arithmetic by
    # contract, so any device op or host sync here is a bug
    "koordinator_tpu/state/workingset.py",
    "koordinator_tpu/service/server.py",
    "koordinator_tpu/service/admission.py",
    # the multi-tenant pool (DESIGN §20): its lane staging + dispatch
    # run on the gate's executor thread — the serving hot path
    "koordinator_tpu/service/tenancy.py",
    "koordinator_tpu/service/failover.py",
    # the AOT warm pool (DESIGN §21): serve() sits on every adopted
    # solve call — a stray sync or implicit jit there is per-tick cost
    "koordinator_tpu/service/warmpool.py",
    "koordinator_tpu/parallel/mesh.py",
    # the auditor runs between scheduling rounds, not in the solve loop,
    # but it handles staged device values: its ONE intentional read-back
    # (the parity probe) is allowlisted by name; anything else is a bug
    "koordinator_tpu/scheduler/auditor.py",
    # the pipelined tick path: the coordinator half (submit/prestage)
    # must stay taint-clean — the solve's read-back belongs to exactly
    # one publish-side site (InFlightSchedule.finalize); a stray sync
    # here would put the device compute back on the round's critical
    # path
    "koordinator_tpu/scheduler/pipeline.py",
    # the trace fabric: span emission rides inside every hot module
    # above, so the obs layer itself must be provably taint-clean — its
    # ONE intentional read-back (the explain breakdown's host
    # materialization, obs/explain.py) is allowlisted by name; any
    # other device sync here would hide a per-tick stall inside
    # "observability"
    "koordinator_tpu/obs/*.py",
)

#: attribute -> lock maps for the concurrency-critical classes the
#: incremental staging path relies on (docs/DESIGN.md §11)
LOCK_SPECS = (
    LockSpec(
        path="koordinator_tpu/scheduler/cache.py",
        class_name="SchedulerCache",
        lock="_lock",
        attrs=(
            "nodes", "pods", "pending", "assumed", "node_metrics",
            "gangs", "quotas", "reservations",
        ),
    ),
    LockSpec(
        path="koordinator_tpu/state/cluster.py",
        class_name="ClusterDeltaTracker",
        lock="_lock",
        attrs=("epoch", "structure_epoch", "_marks"),
    ),
    LockSpec(
        path="koordinator_tpu/models/placement.py",
        class_name="StagedStateCache",
        lock="_lock",
        attrs=(
            "arrays", "state", "tracker", "seen_epoch", "epoch",
            "last_delta", "last_path", "last_now", "_pinned",
            "_wire_delta",
        ),
        # the working-set demote hooks (DESIGN §26) hold the SAME lock
        # via non-blocking acquire/try/finally — `with` would block,
        # and the contract is "a busy cache refuses, never stalls"
        exempt_methods=("__init__", "demote_device", "demote_cold"),
    ),
    # the HBM working-set ledger (DESIGN §26): touched from every
    # staging call site and the chaos saboteur; the `*_locked` helpers
    # are only entered under the lock (the SLO controller's
    # _step_locked precedent)
    LockSpec(
        path="koordinator_tpu/state/workingset.py",
        class_name="WorkingSetManager",
        lock="_lock",
        attrs=(
            "_residents", "_budget", "_squeeze", "_clock", "_auto",
            "_seq", "_events", "_counts", "_faults", "_oversubscribed",
        ),
        exempt_methods=(
            "__init__", "_used_locked", "_effective_budget_locked",
            "_count_locked", "_event_locked", "_publish_locked",
        ),
    ),
    # the pipelined tick loop's state machine: the coordinator thread
    # (submit/drain/status) and the publisher worker (retire) share it
    LockSpec(
        path="koordinator_tpu/scheduler/pipeline.py",
        class_name="TickPipeline",
        lock="_lock",
        attrs=(
            "_inflight", "_pending_error", "_rounds", "_last",
            "_stopped",
        ),
    ),
    # the anti-entropy auditor: sweeps run on the scheduling-loop
    # thread, status() is read from debug-mux handler threads
    LockSpec(
        path="koordinator_tpu/scheduler/auditor.py",
        class_name="StateAuditor",
        lock="_lock",
        attrs=(
            "_promotion_pending", "_rounds_since", "_probe_cursor",
            "_unrepairable", "sweeps", "detections", "repairs",
            "last_report",
        ),
    ),
    LockSpec(
        path="koordinator_tpu/service/admission.py",
        class_name="AdmissionGate",
        lock="_lock",
        attrs=("_lanes", "_closed", "_stats", "_undelivered",
               "_tenant_stats"),
    ),
    # the multi-tenant pool's weight registry (DESIGN §20): read on the
    # gate's submit/claim paths (under the gate lock — a documented
    # gate→registry order edge), written by operators/tests
    LockSpec(
        path="koordinator_tpu/service/tenancy.py",
        class_name="TenantRegistry",
        lock="_lock",
        attrs=("_weights",),
    ),
    # the AOT warm pool (docs/DESIGN.md §21): adopted solve calls
    # serve() under it, the background persister and promotion
    # restores mutate it, debug muxes read status(). ``serving`` is a
    # plain fast-path flag read without the lock (same contract as
    # DeviceObservatory.enabled); everything else is mapped. The pool
    # lock never nests with any other mapped lock (compiles and disk
    # I/O always run outside it).
    LockSpec(
        path="koordinator_tpu/service/warmpool.py",
        class_name="WarmPool",
        lock="_lock",
        attrs=(
            "_cache", "_configured", "_single_device", "_reg", "_execs",
            "_persisted",
            "_manifest", "hits", "misses", "rejects", "quarantined",
            "served",
            "load_s_total", "compiles", "last_restore", "last_error",
            "_bg_thread", "_bg_stop", "_restore_thread",
        ),
    ),
    # the failover state machine: scheduler ticks, recovery probes, and
    # status() readers all cross it (docs/DESIGN.md §13)
    LockSpec(
        path="koordinator_tpu/service/failover.py",
        class_name="FailoverSolver",
        lock="_lock",
        attrs=(
            "degraded", "degraded_since", "consecutive_failures",
            "healthy_probes", "flips_to_degraded", "flips_to_remote",
            "local_solves", "last_error", "last_mode",
        ),
    ),
    # the supervisor: the monitor thread, start()/stop() callers, and
    # status() readers share the child handle and restart bookkeeping
    LockSpec(
        path="koordinator_tpu/service/supervisor.py",
        class_name="SolverSupervisor",
        lock="_lock",
        attrs=(
            "_proc", "state", "restarts_total",
            "consecutive_probe_failures", "last_exit_code",
            "_backoff_attempt", "_spawned_at", "_ready_since_spawn",
            "_respawn_warm", "respawns_warm_total", "_warm_probe_at",
        ),
    ),
    # the trace fabric (docs/DESIGN.md §16): every thread in the
    # process — coordinator, publisher, gate executor, sidecar
    # handlers, debug-mux readers — appends into one ring
    LockSpec(
        path="koordinator_tpu/obs/trace.py",
        class_name="SpanTracer",
        lock="_lock",
        attrs=("_events", "_open", "_stuck", "_round", "_next_span",
               "_emitted"),
    ),
    # per-pod timelines: informer intake, the tick path, and the
    # publish side all stamp stages; debug-mux readers snapshot
    LockSpec(
        path="koordinator_tpu/obs/timeline.py",
        class_name="PodTimelines",
        lock="_lock",
        attrs=("_active", "_completed", "_dropped", "_on_drop"),
    ),
    # the streaming intake (docs/DESIGN.md §22): submitter threads
    # admit, the loop thread takes rounds, the pipeline's publisher
    # resolves outcomes, debug-mux readers snapshot — one condition
    # guards it all (shared with the loop's trigger wait)
    LockSpec(
        path="koordinator_tpu/scheduler/streaming.py",
        class_name="ArrivalGate",
        lock="_lock",
        attrs=("_lanes", "_by_uid", "_inflight", "_waiting",
               "_resolved", "_resolved_map", "_stats"),
    ),
    # the streaming loop's own bookkeeping (round counters, the
    # replayable round log): loop thread writes, status() readers and
    # the publisher-thread round resolution cross it
    LockSpec(
        path="koordinator_tpu/scheduler/streaming.py",
        class_name="StreamingLoop",
        lock="_lock",
        attrs=("_rounds", "_skipped", "_last_trigger",
               "_last_fired_at", "round_log"),
    ),
    # the flight recorder: tick paths record, anomaly paths trigger
    # (possibly from other threads), the mux reads dumps
    LockSpec(
        path="koordinator_tpu/obs/flight.py",
        class_name="FlightRecorder",
        lock="_lock",
        attrs=("_ring", "_dumps", "_last_dump", "_dump_dir",
               "_min_interval_s", "_seq", "_files", "_max_files",
               "_payload_hooks"),
    ),
    # the serving SLO controller (docs/DESIGN.md §25): the loop thread
    # reconciles, promotion hooks adopt published knob state from the
    # elector callback, debug-mux/flight readers snapshot the decision
    # and observation rings — one lock over policy state and both rings
    LockSpec(
        path="koordinator_tpu/control/slo.py",
        class_name="ServingSLOController",
        lock="_lock",
        attrs=("_ring", "_obs_ring", "_decisions_total",
               "_last_reconcile_at", "_adopted", "_seq",
               "_breach", "_under", "_relax_cap", "_last_relax",
               "_wm_raise_ok", "_last_decision_now"),
        # _step_locked is the lock-held policy body: both call sites
        # (step(), reconcile()) enter it inside `with self._lock`
        exempt_methods=("__init__", "_step_locked"),
    ),
    # the migration arbiter (docs/DESIGN.md §27): every eviction source
    # (preemption solve, defrag drain, rebalance sweep, working-set
    # demotion notes) requests from its own thread; debug-mux/flight
    # readers snapshot the decision ring — one lock over the budget,
    # the sliding windows, and the ring. It is a LEAF lock: request()
    # is called with scheduler/cache locks already held, and the
    # arbiter never calls out while holding it.
    LockSpec(
        path="koordinator_tpu/control/migration.py",
        class_name="MigrationArbiter",
        lock="_lock",
        attrs=("_budget", "_ring", "_node_times", "_lane_times",
               "_gang_times", "_node_last", "_round_key", "_round_count",
               "_requests_total", "_admitted_total", "_deferred_total",
               "_deferred_reasons", "_seq"),
        # the _locked helpers are the lock-held arbitration body: every
        # call site enters them inside `with self._lock`
        exempt_methods=("__init__", "_request_locked", "_refusal_locked",
                        "_commit_locked", "_purge_locked"),
    ),
    # the closed-loop defrag controller (docs/DESIGN.md §27): the loop
    # thread reconciles on the pump, debug-mux/flight readers snapshot
    # the decision and observation rings
    LockSpec(
        path="koordinator_tpu/control/migration.py",
        class_name="DefragController",
        lock="_lock",
        attrs=("_ring", "_obs_ring", "_streak", "_last_decision_now",
               "_last_reconcile_at", "_decisions_total", "_seq"),
        # _step_locked is the lock-held policy body (same contract as
        # ServingSLOController): step()/reconcile()/replay enter it
        # under the owning instance's lock
        exempt_methods=("__init__", "_step_locked"),
    ),
    # the device-cost observatory (docs/DESIGN.md §17): instrumented
    # jit calls record from solve threads, the monitoring listener
    # fires from whichever thread compiles, analyze()/status() run from
    # debug-mux handlers and bench harnesses. ``enabled`` and
    # ``_profile_hot`` are plain fast-path flags read without the lock
    # (same contract as SpanTracer.enabled); everything else is mapped.
    LockSpec(
        path="koordinator_tpu/obs/device.py",
        class_name="DeviceObservatory",
        lock="_lock",
        attrs=(
            "_seen", "_fn_cache_sizes", "_ring", "_pending", "_analyses",
            "_analysis_order",
            "_padding", "_owners", "_seq", "_compiles_total",
            "_xla_compiles", "_xla_compile_s", "_profile_dir",
            "_profile_min_interval_s", "_profile_max_windows",
            "_profile_armed", "_profile_remaining", "_profile_path",
            "_profile_last_at", "_profile_windows", "_profile_error",
        ),
    ),
)

#: the delta/full lowering pair and the shared per-row helper registry
#: both paths must route row values through
PARITY_SPECS = (
    # lower_node_rows — the auditor's parity-probe lowering — is held
    # to the same registry as the full/delta pair: a probe that
    # computed rows its own way could cry drift (or miss it) purely
    # from divergent arithmetic
    ParitySpec(
        path="koordinator_tpu/state/cluster.py",
        funcs=("lower_nodes", "lower_nodes_delta", "lower_node_rows"),
        required_helpers=(
            "_node_metric_row", "_node_hold_rows", "_clip_i32",
            "resources_to_vector",
        ),
        allowed_helpers=("_metric_fresh",),
    ),
    # the sharded staging row path (ISSUE 10): pad_node_rows builds the
    # inert padding rows every node-sharded device_put appends — held
    # to the same registry discipline as the lowering pair, so a
    # padding row is always "a permanently empty node" built by the
    # shared helpers and never an inline per-caller fold that could
    # drift from what an unschedulable zero node lowers to
    ParitySpec(
        path="koordinator_tpu/state/cluster.py",
        funcs=("pad_node_rows",),
        required_helpers=("_pad_width", "_pad_axis0", "_pad_names"),
    ),
)


#: every mapped lock as a node of the whole-program lock-order graph:
#: the seventeen LockSpec classes' primary locks plus the observatory's
#: documented secondary lock (``_profile_io_lock`` OUTER, ``_lock``
#: inner — obs/device.py) so the documented order is machine-checked
#: RLock-backed classes: same-instance re-acquisition is legal, so the
#: static pass suppresses their self-edges (scheduler/cache.py:42,
#: scheduler/auditor.py:109)
_REENTRANT_CLASSES = frozenset({"SchedulerCache", "StateAuditor"})

LOCK_NODES = tuple(
    LockNode(path=spec.path, class_name=spec.class_name, lock=spec.lock,
             reentrant=spec.class_name in _REENTRANT_CLASSES)
    for spec in LOCK_SPECS
) + (
    LockNode(path="koordinator_tpu/obs/device.py",
             class_name="DeviceObservatory", lock="_profile_io_lock"),
)

#: pin protocols the donation-safety rule enforces: the staged device
#: generation may only be donated when provably not held by an
#: in-flight solve (the PR 11 scatter-clobber invariant)
PIN_SPECS = (
    PinSpec(
        path="koordinator_tpu/models/placement.py",
        class_name="StagedStateCache",
        attr="state",
        pin_attr="_pinned",
    ),
)

#: the warm path never donates (DESIGN §19.2 / §21): every jit factory
#: in these modules must declare donate_argnums=() — the warm pool
#: stores and replays serialized executables, and a donated program
#: replayed from a persistent store mis-applies its alias map on this
#: jax line. The companion adopt-site check (DonationRule) additionally
#: refuses donating bindings at every WARM_POOL.adopt call repo-wide.
NO_DONATE_MODULES = (
    "koordinator_tpu/service/warmpool.py",
)

#: determinism-taint scope: the hot modules plus the wire codec and its
#: client/server callers — everything whose outputs the oracle parity
#: and chaos bit-identity tests compare
DETERMINISM_MODULES = HOT_MODULES + (
    "koordinator_tpu/service/codec.py",
    "koordinator_tpu/service/client.py",
    # the SLO controller's decision log must replay bit-for-bit from
    # its recorded observation ring (DESIGN §25) — no wall clocks or
    # ambient randomness may leak into the policy
    "koordinator_tpu/control/slo.py",
    # the migration arbiter's decision ring must replay bit-for-bit
    # (replay_requests, DESIGN §27) and the defrag controller's policy
    # must replay from its observation ring — same contract as slo.py
    "koordinator_tpu/control/migration.py",
)


# -- graftcheck v3: shape-flow (docs/DESIGN.md §23) --------------------------

#: the repo bucket family — THE sanctioners of the shape-flow lattice.
#: A value returned by any of these is ``bucketed``: finite image under
#: the config bounds, so a finite signature contribution. The pure
#: int->int computers carry ``exempt_body=True`` (their bodies ARE the
#: bucket math); the padding helpers stay ``False`` — their bodies are
#: HELD to the discipline, which is what makes a stripped bucket call
#: inside them machine-detectable (tests/test_graftcheck_v3.py teeth).
BUCKET_FAMILY = (
    BucketFn(name="pow2_quarter_bucket",
             path="koordinator_tpu/parallel/mesh.py",
             qualname="pow2_quarter_bucket", exempt_body=True),
    BucketFn(name="shard_node_bucket",
             path="koordinator_tpu/parallel/mesh.py",
             qualname="shard_node_bucket", exempt_body=True),
    BucketFn(name="shard_tile_bucket",
             path="koordinator_tpu/parallel/mesh.py",
             qualname="shard_tile_bucket", exempt_body=True),
    BucketFn(name="node_bucket", path="koordinator_tpu/service/tenancy.py",
             qualname="node_bucket", exempt_body=True),
    BucketFn(name="pod_bucket", path="koordinator_tpu/service/tenancy.py",
             qualname="pod_bucket", exempt_body=True),
    BucketFn(name="lane_bucket", path="koordinator_tpu/service/tenancy.py",
             qualname="lane_bucket", exempt_body=True),
    BucketFn(name="pod_bucket",
             path="koordinator_tpu/models/placement.py",
             qualname="PlacementModel.pod_bucket", exempt_body=True),
    BucketFn(name="resv_bucket",
             path="koordinator_tpu/models/placement.py",
             qualname="PlacementModel.resv_bucket", exempt_body=True),
    BucketFn(name="victim_bucket",
             path="koordinator_tpu/models/placement.py",
             qualname="PlacementModel.victim_bucket", exempt_body=True),
    BucketFn(name="preemptor_bucket",
             path="koordinator_tpu/models/placement.py",
             qualname="PlacementModel.preemptor_bucket", exempt_body=True),
    BucketFn(name="dirty_row_bucket",
             path="koordinator_tpu/ops/binpack.py",
             qualname="dirty_row_bucket", exempt_body=True),
    BucketFn(name="coalesce_pod_bucket",
             path="koordinator_tpu/service/admission.py",
             qualname="coalesce_pod_bucket", exempt_body=True),
    BucketFn(name="sweep_candidate_bucket",
             path="koordinator_tpu/ops/rebalance.py",
             qualname="sweep_candidate_bucket", exempt_body=True),
    # the array sanctioners: their RETURNS are bucket-shaped; their
    # bodies stay under the rule (strip a bucket call -> convicted)
    BucketFn(name="_pad_pods", path="koordinator_tpu/models/placement.py",
             qualname="PlacementModel._pad_pods"),
    BucketFn(name="_pad_resv", path="koordinator_tpu/models/placement.py",
             qualname="PlacementModel._pad_resv"),
    BucketFn(name="bucket_row_update",
             path="koordinator_tpu/ops/binpack.py",
             qualname="bucket_row_update", exempt_body=True),
    BucketFn(name="pad_node_rows",
             path="koordinator_tpu/state/cluster.py",
             qualname="pad_node_rows"),
    BucketFn(name="pad_node_arrays",
             path="koordinator_tpu/parallel/mesh.py",
             qualname="pad_node_arrays"),
)

#: where the bucket-flow pass convicts: the hot modules plus the
#: streaming front end, the shared test/bench world builders, and the
#: bench legs themselves (the engine's module universe includes the
#: repo-root scripts for exactly this)
SHAPEFLOW_SCOPE = HOT_MODULES + (
    "koordinator_tpu/scheduler/streaming.py",
    "koordinator_tpu/testing/*.py",
    "bench.py",
)

# -- signature-space bounds (the "finite" in "finite recompile surface") -----
# Every bound is a documented config/deployment cap, not a guess pulled
# from the air: the enumeration's claim is "under these caps, the
# reachable aval-signature set is THIS", and the caps are the same ones
# the bench legs and SchedulerConfig already encode.

#: node-count cap: the 100k-node single-domain roadmap target (item 3,
#: KTPU_BENCH_SHARD_NODES leg 14) rounded up to the next power of two
MAX_NODES = 131072
#: per-round pod batch cap: bench churn waves peak at 10k pods/round
#: (legs 9/14); one quarter-pow2 octave of headroom
MAX_PODS = 16384
#: reservation-table cap (bench/test tables run <=256; pow2 headroom)
MAX_RESV = 4096
#: resident-pods-per-node cap for the victim axis: kubelet's max-pods
#: default is 110; pow2 headroom for dense BE packing (bench leg 19
#: runs ~2 residents/node at 5k nodes, chaos storms reach dozens)
MAX_RESIDENTS = 512
#: coalesced-lane cap: AdmissionConfig.capacity default — the gate can
#: never dispatch more lanes than it can queue
MAX_COALESCED_LANES = 128
#: tenant-lane cap: tenancy.MAX_TRACKED_TENANTS
MAX_TENANT_LANES = 256
#: lane shard sweeps: the measured mesh shapes (virtual 8-device CPU
#: mesh and its 2/4-way splits; DESIGN §19/§20)
SHARD_SWEEP = ((("shards", 1),), (("shards", 2),), (("shards", 4),),
               (("shards", 8),))
#: node-shard sweep EXCLUDES 1: shard_node_bucket is the identity at
#: one shard by design (a single-device world never pads), and the
#: sharded solver bindings only exist on multi-device meshes
MULTI_SHARD_SWEEP = ((("shards", 2),), (("shards", 4),),
                     (("shards", 8),))

_POD_AXIS = AxisSpec(
    axis="pods", bucket="koordinator_tpu.parallel.mesh:pow2_quarter_bucket",
    kwargs_options=((("floor", 64),),), bound=MAX_PODS,
    bound_source="bench churn wave cap (legs 9/14)",
)
_RESV_AXIS = AxisSpec(
    axis="resv",
    bucket="koordinator_tpu.models.placement:PlacementModel.resv_bucket",
    bound=MAX_RESV, bound_source="reservation-table cap",
)
_DIRTY_AXIS = AxisSpec(
    axis="dirty_rows", bucket="koordinator_tpu.ops.binpack:dirty_row_bucket",
    bound=MAX_NODES, bound_source="node-count cap (roadmap item 3)",
)
_COALESCE_POD_AXIS = AxisSpec(
    axis="pods",
    bucket="koordinator_tpu.service.admission:coalesce_pod_bucket",
    bound=MAX_PODS, bound_source="bench churn wave cap",
)
_TENANT_LANE_AXIS = AxisSpec(
    axis="lanes", bucket="koordinator_tpu.service.tenancy:lane_bucket",
    kwargs_options=SHARD_SWEEP, bound=MAX_TENANT_LANES,
    bound_source="tenancy.MAX_TRACKED_TENANTS",
)
_TENANT_NODE_AXIS = AxisSpec(
    axis="nodes", bucket="koordinator_tpu.service.tenancy:node_bucket",
    bound=MAX_NODES, bound_source="node-count cap (roadmap item 3)",
)
_TENANT_POD_AXIS = AxisSpec(
    axis="pods", bucket="koordinator_tpu.service.tenancy:pod_bucket",
    bound=MAX_PODS, bound_source="bench churn wave cap",
)
_SHARD_NODE_AXIS = AxisSpec(
    axis="nodes", bucket="koordinator_tpu.parallel.mesh:shard_node_bucket",
    kwargs_options=MULTI_SHARD_SWEEP, bound=MAX_NODES,
    bound_source="node-count cap (roadmap item 3)",
)

_VICTIM_AXIS = AxisSpec(
    axis="victims",
    bucket="koordinator_tpu.models.placement:PlacementModel.victim_bucket",
    bound=MAX_RESIDENTS,
    bound_source="kubelet max-pods default (110), pow2 headroom",
)
_PREEMPTOR_AXIS = AxisSpec(
    axis="preemptors",
    bucket="koordinator_tpu.models.placement:PlacementModel."
           "preemptor_bucket",
    bound=MAX_PODS,
    bound_source="bench churn wave cap (storm leg 19 scans arrivals)",
)

_SOLVE_AXES = (_POD_AXIS, _RESV_AXIS)
#: the batched solve's quasi-static axes: one value per deployment
#: shape (structure epochs), not a per-tick surface — the sentinel
#: holds them constant-within-window instead of image-membered
_SOLVE_STRUCTURAL = ("nodes", "features")

#: every DEVICE_OBS.jit binding in the repo, with its declared
#: signature space. The signature-space pass cross-checks this registry
#: against the binding census BOTH ways (an undeclared binding and a
#: stale declaration each fail), enumerates the images from the LIVE
#: bucket functions, and exports the result to the JSON sidecar and the
#: runtime sentinel (testing/shapeflow.py).
BINDING_SPECS = (
    BindingSpec(name="solve_batch",
                path="koordinator_tpu/models/placement.py",
                axes=_SOLVE_AXES, structural=_SOLVE_STRUCTURAL),
    BindingSpec(name="sidecar_solve_batch",
                path="koordinator_tpu/service/server.py",
                axes=_SOLVE_AXES, structural=_SOLVE_STRUCTURAL),
    BindingSpec(name="failover_local_solve",
                path="koordinator_tpu/service/failover.py",
                axes=_SOLVE_AXES, structural=_SOLVE_STRUCTURAL),
    BindingSpec(name="coalesced_solve",
                path="koordinator_tpu/service/admission.py",
                axes=(AxisSpec(axis="lanes", bound=MAX_COALESCED_LANES,
                               bound_source="AdmissionConfig.capacity"),
                      _COALESCE_POD_AXIS),
                structural=_SOLVE_STRUCTURAL,
                note="lane axis is config-capped raw by design (PR 8): "
                     "each K <= capacity reuses its program"),
    BindingSpec(name="coalesced_solve_assign",
                path="koordinator_tpu/service/admission.py",
                axes=(AxisSpec(axis="lanes", bound=MAX_COALESCED_LANES,
                               bound_source="AdmissionConfig.capacity"),
                      _COALESCE_POD_AXIS),
                structural=_SOLVE_STRUCTURAL),
    BindingSpec(name="tenant_pool_solve",
                path="koordinator_tpu/service/tenancy.py",
                axes=(_TENANT_LANE_AXIS, _TENANT_NODE_AXIS,
                      _TENANT_POD_AXIS),
                structural=("features",)),
    BindingSpec(name="tenant_pool_solve_full",
                path="koordinator_tpu/service/tenancy.py",
                axes=(_TENANT_LANE_AXIS, _TENANT_NODE_AXIS,
                      _TENANT_POD_AXIS),
                structural=("features",)),
    BindingSpec(name="preempt_solve",
                path="koordinator_tpu/models/placement.py",
                axes=(_VICTIM_AXIS,), structural=_SOLVE_STRUCTURAL,
                note="joint place+evict per-preemptor victim selection "
                     "(ops/preempt.select_victims, DESIGN §24)"),
    BindingSpec(name="preempt_solve_scan",
                path="koordinator_tpu/models/placement.py",
                axes=(_VICTIM_AXIS, _PREEMPTOR_AXIS),
                structural=_SOLVE_STRUCTURAL,
                note="scanned storm variant: whole preemptor batch in "
                     "one program"),
    BindingSpec(name="defrag_repack",
                path="koordinator_tpu/models/placement.py",
                axes=(_VICTIM_AXIS,), structural=_SOLVE_STRUCTURAL,
                note="headroom repack: drain a fragmented node for a "
                     "gang-sized hole"),
    BindingSpec(name="rebalance_sweep",
                path="koordinator_tpu/ops/rebalance.py",
                axes=(AxisSpec(
                    axis="candidates",
                    bucket="koordinator_tpu.ops.rebalance:"
                           "sweep_candidate_bucket",
                    bound=MAX_PODS,
                    bound_source="bench churn wave cap (a sweep scans "
                                 "at most one round's pod census)"),),
                structural=("features",),
                note="device Balance sweep: flattened host-ordered "
                     "candidate scan, bit-parity oracle in "
                     "descheduler/loadaware.py (DESIGN §27)"),
    BindingSpec(name="scatter_node_rows_donated",
                path="koordinator_tpu/ops/binpack.py",
                axes=(_DIRTY_AXIS,), structural=_SOLVE_STRUCTURAL),
    BindingSpec(name="scatter_node_rows_copied",
                path="koordinator_tpu/ops/binpack.py",
                axes=(_DIRTY_AXIS,), structural=_SOLVE_STRUCTURAL),
    BindingSpec(name="shard_solver",
                path="koordinator_tpu/parallel/mesh.py",
                axes=(_SHARD_NODE_AXIS, _POD_AXIS),
                structural=("features",)),
    BindingSpec(name="shard_full_solver",
                path="koordinator_tpu/parallel/mesh.py",
                axes=(_SHARD_NODE_AXIS, _POD_AXIS, _RESV_AXIS),
                structural=("features",)),
    BindingSpec(name="shard_lane_solver",
                path="koordinator_tpu/parallel/mesh.py",
                axes=(_TENANT_LANE_AXIS, _COALESCE_POD_AXIS),
                structural=_SOLVE_STRUCTURAL),
    BindingSpec(name="shard_tenant_solver",
                path="koordinator_tpu/parallel/mesh.py",
                axes=(_TENANT_LANE_AXIS, _TENANT_NODE_AXIS,
                      _TENANT_POD_AXIS),
                structural=("features",)),
)

#: statics the warm manifest provably keys by value (SolverConfig is a
#: flat NamedTuple of ints/bools — ``_config_key`` tuples it). An
#: adopted binding declaring any OTHER static is unrepresentable in
#: the store and fails warm-coverage.
HASHABLE_STATICS = ("config",)

# -- metrics hygiene (the PR 16 tenant-label class) --------------------------

#: every label on the serving-path registries, with its boundedness
#: story. ``enum`` values are the code-enumerated emit sites (audited
#: here so a new value is a conscious registry edit); ``binding`` is
#: bounded by the DEVICE_OBS.jit binding census above; ``folded``
#: labels carry wire-controlled values folded into a sentinel past the
#: cardinality cap (tenancy.MAX_TRACKED_TENANTS -> OVERFLOW_TENANT).
LABEL_DOMAINS = {
    "result": LabelDomain(kind="enum", values=(
        "scheduled", "unschedulable", "error", "nominated",
        "written", "rate-limited", "refused",
    )),
    "reason": LabelDomain(kind="enum", values=(
        # failure-domain + supervisor + streaming + warm-pool reject
        # reasons; PIPELINE_DRAINS additionally takes bench/test-local
        # values — still call-site-bounded, never wire-controlled
        "solver-unavailable", "crashed", "hung", "down",
        "auditor-sweep", "failover-flip", "standby", "shutdown", "once",
        "truncated", "corrupt", "fingerprint", "oversized",
        "stale-host", "version-skew",
        "capacity", "timeline-capacity", "deadline",
        "overloaded",
        # HBM working-set outcomes (state/workingset.py, DESIGN §26):
        # demotion causes, restage source rungs, alloc-fail boundaries
        "admission", "budget", "alloc-failure",
        "host", "cold",
        "stage", "scatter",
        # migration-arbiter typed refusal reasons (control/migration.py
        # REASONS, DESIGN §27) — the deferral vocabulary, precedence
        # order mirrored in code
        "cooldown", "round-budget", "node-budget", "tenant-budget",
        "gang-min-available",
    )),
    # the migration arbiter's eviction-source vocabulary
    # (control/migration.py SOURCES, DESIGN §27): every path that may
    # evict a resident declares which one it is
    "source": LabelDomain(kind="enum", values=(
        "preemption", "defrag", "rebalance", "workingset",
    )),
    # the working-set residency census gauge (DESIGN §26)
    "rung": LabelDomain(kind="enum", values=("device", "host", "cold")),
    "direction": LabelDomain(kind="enum",
                             values=("to-degraded", "to-remote")),
    "mode": LabelDomain(kind="enum", values=(
        "local-fallback", "local-degraded", "coalesced", "lanes", "solo",
    )),
    "kind": LabelDomain(kind="enum", values=(
        "periodic", "promotion", "manual", "round", "publish",
        "fencing", "solver", "other",
        "cache-bus", "accounting", "device-parity",
    )),
    "boundary": LabelDomain(kind="enum", values=(
        "cache-bus", "accounting", "device-parity",
    )),
    "action": LabelDomain(kind="enum", values=(
        "targeted", "cache-rebuild", "full-restage",
    )),
    "stage": LabelDomain(kind="enum",
                         values=("lower", "stage", "solve", "publish")),
    "trigger": LabelDomain(kind="enum", values=(
        "auditor-detection", "failover-flip", "fencing-abort",
        "pipeline-deferred-error", "deadline-exceeded", "manual",
        "watermark", "deadline", "idle",
    )),
    "lane": LabelDomain(kind="enum", values=("system", "ls", "be")),
    # the SLO controller's typed decision vocabulary (DESIGN §25):
    # every knob it may move and every signal that may move one —
    # control/slo.py KNOBS / SIGNALS are the code-side enumerations
    "knob": LabelDomain(kind="enum", values=(
        "watermark", "deadline", "capacity",
    )),
    "signal": LabelDomain(kind="enum", values=(
        "p99-over", "p99-under", "shed-capacity", "padding-waste",
        # the defrag controller's fragmentation signal (DESIGN §27)
        "frag-over",
    )),
    "buffer": LabelDomain(kind="enum", values=(
        "pod_batch", "resv_table", "dirty_rows", "coalesced_pods",
        "tenant_nodes", "tenant_pods", "tenant_lanes",
        "resident_pods", "preemptor_batch", "sweep_candidates",
    )),
    "outcome": LabelDomain(kind="enum", values=(
        "selected", "reprieved", "evicted",
    )),
    "fn": LabelDomain(kind="binding"),
    "tenant": LabelDomain(kind="folded", fold_symbol="OVERFLOW_TENANT"),
}

METRICS_SPEC = MetricsSpec(
    components_path="koordinator_tpu/metrics/components.py",
    registries=("SCHEDULER_METRICS", "DEVICE_METRICS", "SOLVER_METRICS",
                "WORKINGSET_METRICS"),
    label_domains=LABEL_DOMAINS,
)


def default_rules():
    return (
        HostSyncRule(scope=HOT_MODULES),
        LockDisciplineRule(specs=LOCK_SPECS),
        DeltaParityRule(specs=PARITY_SPECS),
        JitHygieneRule(scope=HOT_MODULES),
        DeadImportRule(scope=HOT_MODULES),
        # whole-program passes (ISSUE 9): cross-module sync taint, the
        # lock acquisition order, donation liveness, determinism taint
        SyncReachRule(scope=HOT_MODULES),
        LockOrderRule(locks=LOCK_NODES),
        DonationRule(pin_specs=PIN_SPECS,
                     no_donate_globs=NO_DONATE_MODULES),
        DeterminismRule(scope=DETERMINISM_MODULES),
        # whole-program passes (ISSUE 15, docs/DESIGN.md §23): the
        # static shape-flow trio proving the recompile surface finite
        # and warm-coverable, plus the metric-exposition audit
        BucketFlowRule(scope=SHAPEFLOW_SCOPE, buckets=BUCKET_FAMILY),
        SignatureSpaceRule(specs=BINDING_SPECS),
        WarmCoverageRule(specs=BINDING_SPECS, hot_scope=HOT_MODULES,
                         hashable_statics=HASHABLE_STATICS),
        MetricsHygieneRule(spec=METRICS_SPEC),
    )


__all__ = [
    "AxisSpec",
    "BINDING_SPECS",
    "BUCKET_FAMILY",
    "BindingSpec",
    "BucketFlowRule",
    "BucketFn",
    "DETERMINISM_MODULES",
    "HASHABLE_STATICS",
    "HOT_MODULES",
    "LABEL_DOMAINS",
    "LabelDomain",
    "METRICS_SPEC",
    "MetricsHygieneRule",
    "MetricsSpec",
    "SHAPEFLOW_SCOPE",
    "SignatureSpaceRule",
    "WarmCoverageRule",
    "LOCK_NODES",
    "LOCK_SPECS",
    "NO_DONATE_MODULES",
    "PARITY_SPECS",
    "PIN_SPECS",
    "DeadImportRule",
    "DeltaParityRule",
    "DeterminismRule",
    "DonationRule",
    "HostSyncRule",
    "JitHygieneRule",
    "LockDisciplineRule",
    "LockNode",
    "LockOrderRule",
    "LockSpec",
    "ParitySpec",
    "PinSpec",
    "SyncReachRule",
    "default_rules",
]
