"""sync-reach: interprocedural host-sync taint over the call graph.

The local ``host-sync`` rule dies at the function boundary and only
scans ``HOT_MODULES`` — a ``jax.device_get`` buried two calls below
``schedule_async`` in an un-scoped helper module was invisible (the
exact shape of the PR 10-13 bug class: the sync lives where nobody
lints). This rule closes that hole:

1. every function in the WHOLE repo is scanned for unconditional sync
   sites — ``jax.device_get``, ``jax.block_until_ready``, any
   ``.block_until_ready()`` method call;
2. sync reachability propagates backward over the resolved call graph
   (:class:`~koordinator_tpu.analysis.graftcheck.callgraph.Program`),
   carrying a bounded witness path;
3. a hot-module function whose call site reaches a sync site located
   OUTSIDE the hot scope is a violation, reported AT THE CALL SITE in
   the hot module (so allowlist entries stay function+symbol scoped,
   like the local rule's).

Sync sites inside hot modules are deliberately NOT re-reported here:
they are the local rule's jurisdiction, already judged (or allowlisted
by name) where they live — re-flagging every caller of an allowlisted
barrier would turn one justified sync into a cascade of findings.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Sequence, Set, Tuple

from koordinator_tpu.analysis.graftcheck.engine import (
    ModuleFile,
    Violation,
    attr_chain,
    module_matches,
)
from koordinator_tpu.analysis.graftcheck.callgraph import Program

#: witness sync sites carried per function (bounded so SCC propagation
#: stays linear; one witness is enough to fix the finding)
_MAX_WITNESSES = 3


@dataclasses.dataclass(frozen=True)
class _SyncSite:
    symbol: str        # "jax.device_get" | ".block_until_ready()" | ...
    path: str
    line: int


def _direct_syncs(fn_node: ast.AST, path: str) -> List[_SyncSite]:
    """Unconditional host syncs in one function body, nested defs
    excluded (they carry their own entry in the function table)."""
    out: List[_SyncSite] = []
    stack = list(ast.iter_child_nodes(fn_node))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func) or ""
            if chain == "jax.device_get":
                out.append(_SyncSite("jax.device_get", path, node.lineno))
            elif chain == "jax.block_until_ready":
                out.append(_SyncSite(
                    "jax.block_until_ready", path, node.lineno
                ))
            elif isinstance(node.func, ast.Attribute) \
                    and node.func.attr == "block_until_ready":
                out.append(_SyncSite(
                    ".block_until_ready()", path, node.lineno
                ))
        stack.extend(ast.iter_child_nodes(node))
    return out


class SyncReachRule:
    """Whole-program: hot-path functions must not reach a host sync
    hidden in a helper, however many calls deep."""

    name = "sync-reach"
    description = (
        "no hot-path function transitively reaches a jax.device_get / "
        "block_until_ready outside the hot scope (interprocedural)"
    )

    def __init__(self, scope: Sequence[str]):
        self.scope = tuple(scope)

    def check_program(self, program: Program) -> List[Violation]:
        # 1. direct sync sites per function, repo-wide — but only sites
        #    OUTSIDE the hot scope (hot-module sites belong to the
        #    local host-sync rule and its allowlist)
        reach: Dict[str, Tuple[_SyncSite, ...]] = {}
        for key, info in program.functions.items():
            if module_matches(info.path, self.scope):
                continue
            sites = _direct_syncs(info.node, info.path)
            if sites:
                reach[key] = tuple(sites[:_MAX_WITNESSES])

        # 2. backward propagation to a fixpoint: a caller reaches every
        #    sync its callees reach (witnesses bounded + deduped)
        callers: Dict[str, Set[str]] = {}
        for caller, sites in program.calls.items():
            for site in sites:
                callers.setdefault(site.callee, set()).add(caller)
        work = list(reach)
        while work:
            callee = work.pop()
            its = reach.get(callee, ())
            for caller in callers.get(callee, ()):
                info = program.functions.get(caller)
                if info is not None \
                        and module_matches(info.path, self.scope):
                    continue  # hot functions report at their call sites
                have = reach.get(caller, ())
                merged = list(have)
                for s in its:
                    if s not in merged:
                        merged.append(s)
                merged = merged[:_MAX_WITNESSES]
                if tuple(merged) != have:
                    reach[caller] = tuple(merged)
                    work.append(caller)

        # 3. hot-module call sites whose callee reaches a sync
        out: List[Violation] = []
        hot_paths = {
            m.path for m in program.modules
            if module_matches(m.path, self.scope)
        }
        for key, info in program.functions.items():
            if info.path not in hot_paths:
                continue
            for site in program.callees(key):
                witnesses = reach.get(site.callee, ())
                if not witnesses:
                    continue
                w = witnesses[0]
                node = site.node
                line = node.lineno if node is not None else \
                    info.node.lineno
                col = node.col_offset if node is not None else 0
                out.append(Violation(
                    rule=self.name, path=info.path, line=line, col=col,
                    func=info.qualname, symbol=w.symbol,
                    message=(
                        f"call to {site.chain}() reaches {w.symbol} at "
                        f"{w.path}:{w.line} — a host sync hidden "
                        f"outside the hot scope"
                    ),
                ))
        return out

    def check(self, module: ModuleFile) -> List[Violation]:
        """Single-module compatibility: build a one-module program."""
        return self.check_program(Program([module]))
