"""jit-hygiene: explicit static/donate declarations, no per-call scalars.

Every ``jax.jit``/``pjit`` in a hot-path module must declare BOTH its
static surface (``static_argnums``/``static_argnames``) and its
donation surface (``donate_argnums``/``donate_argnames``) — an empty
tuple is a declaration ("nothing static", "nothing donated"); absence
is not. The implicit defaults are where recompile churn and missed
double-buffering hide: a reader (and this checker) can't tell an
audited callsite from an unconsidered one.

Second check: callables bound from ``X = jax.jit(...)`` must not be
invoked with per-call-varying Python scalars (``len(...)``,
``int(...)``, ``time.*()`` results as positional args) — each distinct
value hashes into the jit cache key only if marked static, and if it
is NOT static it becomes a traced 0-d array; either way a value that
changes every tick means a recompile or a retrace per tick.
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Set

from koordinator_tpu.analysis.graftcheck.engine import (
    ModuleFile,
    Violation,
    attr_chain,
    qualname_map,
)

_STATIC_KWS = {"static_argnums", "static_argnames"}
_DONATE_KWS = {"donate_argnums", "donate_argnames"}
#: host-scalar producers that vary per call when fed to a jitted callable
_VARYING_CALLS = {"len", "int", "float", "round"}
_VARYING_CHAINS = ("time.time", "time.perf_counter", "time.monotonic")


def _jit_target(call: ast.Call) -> Optional[ast.Call]:
    """The Call whose keywords carry the jit declaration, if ``call``
    is ``jax.jit(...)``/``pjit(...)`` or ``partial(jax.jit, ...)``.

    An instrumentation wrapper whose factory method is NAMED ``jit``
    and carries a jit factory as an argument (``DEVICE_OBS.jit("name",
    jax.jit(f, ...))``, obs/device.py) delegates its declaration to
    the INNER factory call — the wrapper is call-transparent, so its
    binding is a jitted callable (pass 2 still applies) while
    static/donate completeness is checked where the declaration
    actually lives. Calls that merely take a jit factory as an
    argument without being jit-named are untouched."""
    chain = attr_chain(call.func) or ""
    seg = chain.split(".")[-1] if chain else ""
    if seg in ("jit", "pjit"):
        for a in call.args:
            if isinstance(a, ast.Call):
                inner = _jit_target(a)
                if inner is not None:
                    return inner
        return call
    if seg == "partial" and call.args:
        inner = attr_chain(call.args[0]) or ""
        if inner.split(".")[-1] in ("jit", "pjit"):
            return call
    return None


class JitHygieneRule:
    name = "jit-hygiene"
    description = (
        "hot-path jax.jit/pjit callsites declare static_arg* and "
        "donate_arg* explicitly; jitted callables never take per-call-"
        "varying Python scalars"
    )

    def __init__(self, scope: Sequence[str]):
        self.scope = tuple(scope)

    def check(self, module: ModuleFile) -> List[Violation]:
        if not module.matches(self.scope):
            return []
        out: List[Violation] = []
        jitted_names: Set[str] = set()
        qmap = qualname_map(module.tree)
        #: declaration carriers already judged — a wrapper call and its
        #: inner factory both resolve to the same target; report once
        judged: Set[int] = set()

        # pass 1: declaration completeness + collect jitted bindings
        for node in ast.walk(module.tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    chain = attr_chain(dec) or ""
                    if chain.split(".")[-1] in ("jit", "pjit"):
                        out.append(Violation(
                            rule=self.name, path=module.path,
                            line=dec.lineno, col=dec.col_offset,
                            func=qmap.get(id(dec), node.name),
                            symbol=chain,
                            message=(
                                f"bare @{chain} on {node.name} declares "
                                f"neither static_arg* nor donate_arg*"
                            ),
                        ))
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                if _jit_target(node.value) is not None:
                    for t in node.targets:
                        seg = (
                            t.attr if isinstance(t, ast.Attribute)
                            else t.id if isinstance(t, ast.Name) else None
                        )
                        if seg is not None:
                            jitted_names.add(seg)
            if not isinstance(node, ast.Call):
                continue
            target = _jit_target(node)
            if target is None or id(target) in judged:
                continue
            judged.add(id(target))
            kws = {kw.arg for kw in target.keywords if kw.arg is not None}
            missing = []
            if not kws & _STATIC_KWS:
                missing.append("static_argnums/static_argnames")
            if not kws & _DONATE_KWS:
                missing.append("donate_argnums/donate_argnames")
            if missing:
                chain = attr_chain(node.func) or "jit"
                out.append(Violation(
                    rule=self.name, path=module.path, line=node.lineno,
                    col=node.col_offset,
                    func=qmap.get(id(node), "<module>"),
                    symbol=chain,
                    message=(
                        f"{chain}(...) does not declare "
                        f"{' or '.join(missing)} — implicit jit "
                        f"surfaces hide recompile churn and missed "
                        f"donation"
                    ),
                ))

        # pass 2: per-call-varying scalars into jitted callables
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            seg = None
            if isinstance(node.func, ast.Attribute):
                seg = node.func.attr
            elif isinstance(node.func, ast.Name):
                seg = node.func.id
            if seg not in jitted_names:
                continue
            for arg in node.args:
                if not isinstance(arg, ast.Call):
                    continue
                achain = attr_chain(arg.func) or ""
                aseg = achain.split(".")[-1] if achain else ""
                if aseg in _VARYING_CALLS or achain in _VARYING_CHAINS:
                    out.append(Violation(
                        rule=self.name, path=module.path,
                        line=arg.lineno, col=arg.col_offset,
                        func=qmap.get(id(node), "<module>"),
                        symbol=achain or aseg,
                        message=(
                            f"jitted callable {seg}(...) fed per-call-"
                            f"varying Python scalar "
                            f"`{ast.unparse(arg)}` — recompile/retrace "
                            f"churn per invocation"
                        ),
                    ))
        return out
