"""metrics-hygiene: the serving-path metric registries stay scrapeable
and label-bounded (the PR 16 tenant-label class, held mechanically).

Two invariants over the declared registries (rules/__init__.
METRICS_SPEC):

1. **Served.** Every scoped registry (SOLVER/SCHEDULER/DEVICE) must be
   merged into at least one debug mux (``MergedGatherer([...])``
   anywhere in the program). A metric nobody can scrape is a metric
   that silently rots — the operator question it answers goes dark.
2. **Bounded labels.** Every label on a scoped metric must have a
   declared domain: ``enum`` (a code-enumerated value set), ``binding``
   (bounded by the DEVICE_OBS.jit binding census — the ``fn`` label),
   or ``folded`` (wire-controlled values folded into a sentinel past a
   cardinality cap — the ``tenant`` label's ``_overflow`` fold). A
   label with no domain is an unbounded exposition: one hostile wire
   value per series, the exact shape PR 16 closed for tenants.

For ``folded`` domains the declared fold symbol must exist in the
program (a fold that was deleted un-bounds the label silently).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Mapping, Sequence, Tuple

from koordinator_tpu.analysis.graftcheck.callgraph import Program
from koordinator_tpu.analysis.graftcheck.engine import (
    ModuleFile,
    Violation,
    attr_chain,
    qualname_map,
)

_METRIC_FACTORIES = frozenset({"counter", "gauge", "histogram"})


@dataclasses.dataclass(frozen=True)
class LabelDomain:
    """How one label name's value set is statically bounded."""

    kind: str                      # "enum" | "binding" | "folded"
    values: Tuple[str, ...] = ()   # enum: the documented value set
    fold_symbol: str = ""          # folded: the sentinel constant name


@dataclasses.dataclass(frozen=True)
class MetricsSpec:
    """The rule's configuration (production values live in
    rules/__init__.METRICS_SPEC; fixtures narrow it)."""

    components_path: str
    registries: Tuple[str, ...]    # scoped registry variable names
    label_domains: Mapping[str, LabelDomain]


class MetricsHygieneRule:
    """Whole-program: scoped registries are mux-served and their
    labels carry declared bounded domains."""

    name = "metrics-hygiene"
    description = (
        "every scoped metric registry is served by a debug mux and "
        "every label has a statically bounded domain or an _overflow "
        "fold"
    )

    def __init__(self, spec: MetricsSpec):
        self.spec = spec

    def check_program(self, program: Program) -> List[Violation]:
        out: List[Violation] = []
        comp = program.by_path.get(self.spec.components_path)
        if comp is None:
            return out
        qmap = qualname_map(comp.tree)

        # which registry variables reach a MergedGatherer anywhere
        gathered = set()
        fold_symbols = set()
        for module in program.modules:
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Call):
                    chain = attr_chain(node.func) or ""
                    if chain.split(".")[-1] == "MergedGatherer":
                        # registries reach the mux as list/tuple
                        # elements OR bare name arguments — both count
                        # as served (a refactor to positional args
                        # must not flag the whole fleet unscrapeable)
                        for arg in node.args:
                            elts = arg.elts if isinstance(
                                arg, (ast.List, ast.Tuple)) else [arg]
                            for e in elts:
                                name = attr_chain(e)
                                if name:
                                    gathered.add(name.split(".")[-1])
            # fold sentinels are MODULE-LEVEL constants (plain or
            # annotated); collecting nested-scope assignments too
            # would let a coincidental function-local name satisfy
            # the deleted-fold check
            for node in module.tree.body:
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            fold_symbols.add(t.id)
                elif isinstance(node, ast.AnnAssign) \
                        and isinstance(node.target, ast.Name):
                    fold_symbols.add(node.target.id)

        # registration census in the components module
        reg_lines: Dict[str, int] = {}
        for node in ast.walk(comp.tree):
            if not isinstance(node, ast.Assign) \
                    or not isinstance(node.value, ast.Call):
                continue
            call = node.value
            chain = attr_chain(call.func) or ""
            parts = chain.split(".")
            # REGISTRY = Registry("name") assignments: remember lines
            if parts[-1] == "Registry" and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                reg_lines[node.targets[0].id] = node.lineno
                continue
            if len(parts) != 2 or parts[0] not in self.spec.registries \
                    or parts[1] not in _METRIC_FACTORIES:
                continue
            metric_name = (
                call.args[0].value
                if call.args and isinstance(call.args[0], ast.Constant)
                else "<dynamic>"
            )
            labels: Tuple[str, ...] = ()
            for kw in call.keywords:
                if kw.arg == "label_names" and isinstance(
                        kw.value, (ast.Tuple, ast.List)):
                    labels = tuple(
                        e.value for e in kw.value.elts
                        if isinstance(e, ast.Constant)
                    )
            func = qmap.get(id(node), "<module>")
            for label in labels:
                domain = self.spec.label_domains.get(label)
                if domain is None:
                    out.append(Violation(
                        rule=self.name, path=comp.path,
                        line=node.lineno, col=node.col_offset,
                        func=func, symbol=str(metric_name),
                        message=(
                            f"label {label!r} on {metric_name!r} has "
                            f"no declared domain — an unbounded label "
                            f"set is one series per hostile value "
                            f"(declare it in LABEL_DOMAINS: enum, "
                            f"binding-bounded, or _overflow-folded)"
                        ),
                    ))
                elif domain.kind == "folded" \
                        and domain.fold_symbol not in fold_symbols:
                    out.append(Violation(
                        rule=self.name, path=comp.path,
                        line=node.lineno, col=node.col_offset,
                        func=func, symbol=str(metric_name),
                        message=(
                            f"label {label!r} on {metric_name!r} "
                            f"declares fold symbol "
                            f"{domain.fold_symbol!r} which no longer "
                            f"exists in the program — the cardinality "
                            f"fold was deleted, un-bounding the label"
                        ),
                    ))

        for reg in self.spec.registries:
            if reg not in gathered:
                out.append(Violation(
                    rule=self.name, path=comp.path,
                    line=reg_lines.get(reg, 0), col=0,
                    func="<module>", symbol=reg,
                    message=(
                        f"registry {reg} is not merged into any debug "
                        f"mux (MergedGatherer) — its metrics are "
                        f"registered but unscrapeable"
                    ),
                ))
        return out

    def check(self, module: ModuleFile) -> List[Violation]:
        return self.check_program(Program([module]))
