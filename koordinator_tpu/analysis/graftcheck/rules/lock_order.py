"""lock-order: a whole-program lock acquisition graph, cycles = deadlock.

The lock-discipline rule proves each mapped attribute is touched under
its own lock; it says nothing about lock NESTING. With seventeen
mapped classes (SchedulerCache, StagedStateCache, TickPipeline,
StateAuditor, SpanTracer, PodTimelines, FlightRecorder,
DeviceObservatory, SolverSupervisor, FailoverSolver, AdmissionGate,
ClusterDeltaTracker, TenantRegistry, WarmPool, ArrivalGate,
StreamingLoop, ServingSLOController) sharing threads — coordinator, publisher, gate executor, sidecar
handlers, debug mux — two code paths that nest the same pair of locks
in opposite orders are a real deadlock waiting on a real interleaving
(the class the reference's Go race detector + mutex profiling covers).

The rule builds a directed graph over the mapped locks:

- node: ``Class.lockattr`` (one per
  :class:`~koordinator_tpu.analysis.graftcheck.rules.lock_discipline.
  LockSpec` plus any extra declared lock, e.g. DeviceObservatory's
  ``_profile_io_lock``);
- edge A -> B when code holding A acquires B: a nested ``with
  self.<other>`` in the same class, or a call under A's hold whose
  callee (transitively, over the call graph) acquires B.

Any cycle — including a self-edge: calling a method that re-acquires
the non-reentrant lock you hold — is a violation. The acyclic graph is
also the contract the runtime shim
(:mod:`koordinator_tpu.testing.lockorder`) asserts under the chaos
suite: every observed runtime acquisition must embed into this order.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Set, Tuple

from koordinator_tpu.analysis.graftcheck.engine import (
    ModuleFile,
    Violation,
)
from koordinator_tpu.analysis.graftcheck.callgraph import Program


@dataclasses.dataclass(frozen=True)
class LockNode:
    """One mapped lock: ``class_name.lock`` in ``path``.

    ``reentrant`` marks RLock-backed locks (SchedulerCache,
    StateAuditor): a method calling a sibling that re-acquires the
    SAME instance's lock is legal there, so self-edges are not emitted
    for reentrant nodes — matching the runtime shim's per-instance
    reentrancy allowance. Cross-class edges are unaffected."""

    path: str
    class_name: str
    lock: str
    reentrant: bool = False

    @property
    def label(self) -> str:
        return f"{self.class_name}.{self.lock}"


@dataclasses.dataclass
class LockEdge:
    """``held`` -> ``acquired``, with one witness site."""

    held: str          # LockNode.label
    acquired: str      # LockNode.label
    path: str
    line: int
    func: str
    via: str           # "nested-with" | "call:<chain>"


def _is_self_lock(expr: ast.expr, lock_attrs: Set[str]) -> Optional[str]:
    """``self.<lock>`` for a mapped lock attr -> the attr name."""
    if isinstance(expr, ast.Attribute) and expr.attr in lock_attrs \
            and isinstance(expr.value, ast.Name) \
            and expr.value.id == "self":
        return expr.attr
    return None


def build_lock_graph(program: Program, locks: Sequence[LockNode]
                     ) -> Tuple[List[LockEdge], Dict[str, Set[str]]]:
    """(edges with witnesses, transitive direct-acquire sets per
    function key). Shared with the runtime shim and the rule tests."""
    by_class: Dict[Tuple[str, str], List[LockNode]] = {}
    for ln in locks:
        by_class.setdefault((ln.path, ln.class_name), []).append(ln)

    # direct acquisitions per function: `with self.<lock>` where the
    # enclosing (path, class) maps that lock attr
    direct: Dict[str, Set[str]] = {}
    for key, info in program.functions.items():
        if info.class_name is None:
            continue
        nodes = by_class.get((info.path, info.class_name))
        if not nodes:
            continue
        attrs = {ln.lock: ln.label for ln in nodes}
        acquired: Set[str] = set()
        for node in ast.walk(info.node):
            if isinstance(node, (ast.With, ast.AsyncWith)):
                for item in node.items:
                    attr = _is_self_lock(item.context_expr, set(attrs))
                    if attr is not None:
                        acquired.add(attrs[attr])
        if acquired:
            direct[key] = acquired

    # transitive: a function may acquire whatever its callees acquire
    may: Dict[str, Set[str]] = {k: set(v) for k, v in direct.items()}
    changed = True
    while changed:
        changed = False
        for caller, sites in program.calls.items():
            have = may.get(caller)
            for site in sites:
                its = may.get(site.callee)
                if not its:
                    continue
                if have is None:
                    have = may.setdefault(caller, set())
                before = len(have)
                have |= its
                if len(have) != before:
                    changed = True

    # edges: regions holding L, then nested withs and call sites
    edges: List[LockEdge] = []
    seen: Set[Tuple[str, str, str, int]] = set()
    reentrant_labels = {ln.label for ln in locks if ln.reentrant}

    def emit(held: str, acquired: str, path: str, line: int, func: str,
             via: str) -> None:
        if held == acquired and held in reentrant_labels:
            # RLock-backed: same-instance re-acquisition is legal and
            # the per-class graph can't tell instances apart, so
            # reentrant self-edges are not reported statically; the
            # runtime shim still flags cross-INSTANCE nesting of the
            # same class when it actually happens
            return
        key = (held, acquired, path, line)
        if key not in seen:
            seen.add(key)
            edges.append(LockEdge(held, acquired, path, line, func, via))

    for key, info in program.functions.items():
        if info.class_name is None:
            continue
        nodes = by_class.get((info.path, info.class_name))
        if not nodes:
            continue
        attrs = {ln.lock: ln.label for ln in nodes}
        call_sites = {
            id(s.node): s for s in program.callees(key)
            if s.node is not None
        }

        def walk(node: ast.AST, held: Optional[str]) -> None:
            if isinstance(node, (ast.With, ast.AsyncWith)):
                inner = held
                for item in node.items:
                    attr = _is_self_lock(item.context_expr, set(attrs))
                    if attr is not None:
                        label = attrs[attr]
                        if inner is not None:
                            emit(inner, label, info.path,
                                 node.lineno, info.qualname,
                                 "nested-with")
                        inner = label
                    else:
                        walk(item.context_expr, held)
                for stmt in node.body:
                    walk(stmt, inner)
                return
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.Lambda)):
                # a nested def runs later, not under this hold — but a
                # closure invoked by a callee while the lock is held
                # would still be caught through the call graph's
                # parent->nested may-invoke edge; keep the textual walk
                # conservative and stop here
                body = node.body if isinstance(node.body, list) \
                    else [node.body]
                for child in body:
                    walk(child, None)
                return
            if isinstance(node, ast.Call) and held is not None:
                site = call_sites.get(id(node))
                if site is not None:
                    for label in sorted(may.get(site.callee, ())):
                        emit(held, label, info.path, node.lineno,
                             info.qualname, f"call:{site.chain}")
            for child in ast.iter_child_nodes(node):
                walk(child, held)

        walk(info.node, None)
    return edges, may


def find_cycles(edges: Sequence[LockEdge]) -> List[List[str]]:
    """Every elementary cycle reachable in the edge set (self-edges
    included), deduped by node set — small graphs, plain DFS."""
    adj: Dict[str, Set[str]] = {}
    for e in edges:
        adj.setdefault(e.held, set()).add(e.acquired)
    cycles: List[List[str]] = []
    seen_sets: Set[frozenset] = set()

    def dfs(start: str, node: str, path: List[str],
            visited: Set[str]) -> None:
        for nxt in sorted(adj.get(node, ())):
            if nxt == start:
                key = frozenset(path)
                if key not in seen_sets:
                    seen_sets.add(key)
                    cycles.append(path + [start])
            elif nxt not in visited and len(path) < 8:
                dfs(start, nxt, path + [nxt], visited | {nxt})

    for start in sorted(adj):
        dfs(start, start, [start], {start})
    return cycles


class LockOrderRule:
    name = "lock-order"
    description = (
        "the mapped locks form an acyclic acquisition graph "
        "(nested-with + call-under-lock edges); any cycle is a "
        "potential deadlock"
    )

    def __init__(self, locks: Sequence[LockNode]):
        self.locks = tuple(locks)

    def check_program(self, program: Program) -> List[Violation]:
        edges, _ = build_lock_graph(program, self.locks)
        out: List[Violation] = []
        for cycle in find_cycles(edges):
            # witness: the first edge of the cycle
            pairs = list(zip(cycle, cycle[1:]))
            witness = None
            for e in edges:
                if (e.held, e.acquired) == pairs[0]:
                    witness = e
                    break
            sites = []
            for a, b in pairs:
                for e in edges:
                    if (e.held, e.acquired) == (a, b):
                        sites.append(
                            f"{a}->{b} at {e.path}:{e.line} ({e.via})"
                        )
                        break
            out.append(Violation(
                rule=self.name,
                path=witness.path if witness else "<lock-graph>",
                line=witness.line if witness else 0,
                col=0,
                func=witness.func if witness else "<lock-graph>",
                symbol="->".join(cycle),
                message=(
                    "lock-order cycle (potential deadlock): "
                    + "; ".join(sites)
                ),
            ))
        return out

    def check(self, module: ModuleFile) -> List[Violation]:
        return self.check_program(Program([module]))
