"""host-sync: no host synchronization on device values in the hot path.

A ``jax.device_get``, ``.block_until_ready()``, ``float()/int()/bool()``
coercion, or ``np.asarray`` on a device value blocks the Python thread
on the accelerator stream — inside the solve loop that turns an async
dispatch pipeline into a lock-step one and costs a round trip per tick.

The rule runs a local (per-function) taint analysis: names assigned
from device-producing expressions — ``jnp.*`` calls, ``jax.device_put``,
``jax.jit(...)``-wrapped callables (configured or discovered from
``X = jax.jit(...)`` bindings), method calls on tainted receivers,
NamedTuple-style wrappers over tainted arguments — are device values;
coercing one to host is a violation. Function parameters start
untainted (a caller that hands host arrays in is fine), so the analysis
under-reports rather than false-positives. ``jax.device_get`` and any
``block_until_ready`` are flagged unconditionally: there is no
legitimate anonymous use of either in the hot path (the intentional
staging barriers are allowlisted by name in graftcheck.toml).
"""

from __future__ import annotations

import ast
from typing import List, Optional, Sequence, Set

from koordinator_tpu.analysis.graftcheck.engine import (
    ModuleFile,
    Violation,
    attr_chain,
)

#: callables whose results are device-resident in this codebase —
#: matched on the last dotted segment (``self._solve`` -> ``_solve``)
DEFAULT_PRODUCERS = frozenset({
    "solve_batch", "schedule_batch", "pallas_solve_batch",
    "scatter_node_rows_donated", "device_put", "_dispatch_solve",
    "_cached_solve", "_jit_solve", "stage_nodes", "stage_pods",
})

_COERCIONS = ("float", "int", "bool")
_NP_SYNCS = ("np.asarray", "np.array", "numpy.asarray", "numpy.array")


def _last_segment(func: ast.AST) -> Optional[str]:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def _root_name(node: ast.AST) -> Optional[str]:
    while isinstance(node, ast.Attribute):
        node = node.value
    return node.id if isinstance(node, ast.Name) else None


class HostSyncRule:
    name = "host-sync"
    description = (
        "no jax.device_get / block_until_ready / float-int-bool coercion "
        "/ np.asarray on device values in hot-path modules"
    )

    def __init__(self, scope: Sequence[str],
                 producers: frozenset = DEFAULT_PRODUCERS):
        self.scope = tuple(scope)
        self.producers = producers

    # -- taint ---------------------------------------------------------------

    def _is_jit_factory(self, node: ast.expr) -> bool:
        """``jax.jit(...)`` / ``pjit(...)`` / ``partial(jax.jit, ...)``
        — an expression whose value is a jit-compiled callable. The
        last-segment name rule deliberately also matches
        instrumentation wrappers whose factory method is NAMED ``jit``
        (``DEVICE_OBS.jit("name", jax.jit(f, ...))``, obs/device.py):
        the wrapper is call-transparent, so its binding produces device
        values exactly like the bare jit. Arbitrary calls that merely
        TAKE a jit factory as an argument (registries, spawners) are
        not factories — over-tainting them would erode the lint."""
        if not isinstance(node, ast.Call):
            return False
        chain = attr_chain(node.func) or ""
        if chain.split(".")[-1] in ("jit", "pjit"):
            return True
        if chain.split(".")[-1] == "partial" and node.args:
            inner = attr_chain(node.args[0]) or ""
            if inner.split(".")[-1] in ("jit", "pjit"):
                return True
        return False

    def _tainted(self, node: ast.AST, tainted: Set[str],
                 producers: Set[str]) -> bool:
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.Attribute):
            chain = attr_chain(node)
            if chain is not None and chain in tainted:
                return True
            return self._tainted(node.value, tainted, producers)
        if isinstance(node, ast.Subscript):
            return self._tainted(node.value, tainted, producers)
        if isinstance(node, ast.Call):
            func = node.func
            chain = attr_chain(func) or ""
            root = chain.split(".")[0] if chain else None
            if root == "jnp":
                return True
            # jax.jit(...)(args): calling the factory's result
            if isinstance(func, ast.Call) and self._is_jit_factory(func):
                return True
            seg = _last_segment(func)
            if seg is not None and (seg in producers or chain in producers):
                return True
            # a method on a device value returns a device value
            # (x._replace, x.astype, x.sum, ...)
            if isinstance(func, ast.Attribute) and self._tainted(
                func.value, tainted, producers
            ):
                return True
            # NamedTuple-ish wrapper over device members stays a device
            # value (NodeState(...), PodBatch.build(...))
            func_root = _root_name(func)
            if func_root is not None and func_root[:1].isupper():
                return any(
                    self._tainted(a, tainted, producers)
                    for a in list(node.args)
                    + [kw.value for kw in node.keywords]
                )
            return False
        if isinstance(node, (ast.BinOp,)):
            return self._tainted(node.left, tainted, producers) or \
                self._tainted(node.right, tainted, producers)
        if isinstance(node, ast.UnaryOp):
            return self._tainted(node.operand, tainted, producers)
        if isinstance(node, ast.Compare):
            return self._tainted(node.left, tainted, producers) or any(
                self._tainted(c, tainted, producers)
                for c in node.comparators
            )
        if isinstance(node, ast.BoolOp):
            return any(
                self._tainted(v, tainted, producers) for v in node.values
            )
        if isinstance(node, ast.IfExp):
            return self._tainted(node.body, tainted, producers) or \
                self._tainted(node.orelse, tainted, producers)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(
                self._tainted(e, tainted, producers) for e in node.elts
            )
        if isinstance(node, ast.Starred):
            return self._tainted(node.value, tainted, producers)
        if isinstance(node, (ast.ListComp, ast.SetComp, ast.GeneratorExp)):
            return self._tainted(node.elt, tainted, producers)
        if isinstance(node, ast.DictComp):
            return self._tainted(node.value, tainted, producers)
        if isinstance(node, ast.NamedExpr):
            return self._tainted(node.value, tainted, producers)
        return False

    # -- violations ----------------------------------------------------------

    def _check_expr(self, node: ast.AST, tainted: Set[str],
                    producers: Set[str], qualname: str, path: str,
                    out: List[Violation]) -> None:
        # ast.walk descends into Lambda bodies too, so closures see the
        # enclosing taint (the probe's ``lambda: np.asarray(solve(...))``
        # pattern) without a separate pass
        for sub in ast.walk(node):
            if not isinstance(sub, ast.Call):
                continue
            func = sub.func
            chain = attr_chain(func) or ""
            if chain == "jax.device_get":
                out.append(self._v(
                    path, sub, qualname, "jax.device_get",
                    "jax.device_get forces a device->host transfer",
                ))
            elif chain == "jax.block_until_ready" or (
                isinstance(func, ast.Attribute)
                and func.attr == "block_until_ready"
            ):
                symbol = (
                    "jax.block_until_ready" if chain ==
                    "jax.block_until_ready" else ".block_until_ready()"
                )
                out.append(self._v(
                    path, sub, qualname, symbol,
                    f"{symbol} stalls the dispatch pipeline",
                ))
            elif chain in _NP_SYNCS and sub.args and self._tainted(
                sub.args[0], tainted, producers
            ):
                out.append(self._v(
                    path, sub, qualname, chain,
                    f"{chain}({ast.unparse(sub.args[0])}) copies a "
                    f"device value to host",
                ))
            elif (
                isinstance(func, ast.Name)
                and func.id in _COERCIONS
                and len(sub.args) == 1
                and not sub.keywords
                and self._tainted(sub.args[0], tainted, producers)
            ):
                out.append(self._v(
                    path, sub, qualname, f"{func.id}()",
                    f"{func.id}({ast.unparse(sub.args[0])}) synchronously "
                    f"reads a device value",
                ))

    def _v(self, path: str, node: ast.AST, qualname: str, symbol: str,
           message: str) -> Violation:
        return Violation(
            rule=self.name, path=path, line=node.lineno,
            col=node.col_offset, func=qualname, symbol=symbol,
            message=message,
        )

    # -- statement walk ------------------------------------------------------

    def _assign_target(self, target: ast.AST, is_tainted: bool,
                       tainted: Set[str]) -> None:
        if isinstance(target, ast.Name):
            (tainted.add if is_tainted else tainted.discard)(target.id)
        elif isinstance(target, ast.Attribute):
            chain = attr_chain(target)
            if chain is not None:
                (tainted.add if is_tainted else tainted.discard)(chain)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign_target(elt, is_tainted, tainted)
        elif isinstance(target, ast.Starred):
            self._assign_target(target.value, is_tainted, tainted)
        # Subscript targets (container element writes) carry no name

    def _scan(self, stmts, tainted: Set[str], producers: Set[str],
              scopes: List[str], path: str, out: List[Violation]) -> None:
        qualname = ".".join(scopes) if scopes else "<module>"
        check = lambda e: e is not None and self._check_expr(
            e, tainted, producers, qualname, path, out
        )
        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in stmt.decorator_list:
                    check(dec)
                for d in stmt.args.defaults + [
                    d for d in stmt.args.kw_defaults if d is not None
                ]:
                    check(d)
                self._scan(
                    stmt.body, set(tainted), set(producers),
                    scopes + [stmt.name], path, out,
                )
            elif isinstance(stmt, ast.ClassDef):
                for dec in stmt.decorator_list:
                    check(dec)
                self._scan(
                    stmt.body, set(tainted), set(producers),
                    scopes + [stmt.name], path, out,
                )
            elif isinstance(stmt, ast.Assign):
                check(stmt.value)
                if self._is_jit_factory(stmt.value):
                    # X = jax.jit(...): X is a device-producing callable
                    for t in stmt.targets:
                        seg = _last_segment(t)
                        if seg is not None:
                            producers.add(seg)
                    continue
                is_t = self._tainted(stmt.value, tainted, producers)
                for t in stmt.targets:
                    if isinstance(t, (ast.Tuple, ast.List)) and isinstance(
                        stmt.value, (ast.Tuple, ast.List)
                    ) and len(t.elts) == len(stmt.value.elts):
                        for te, ve in zip(t.elts, stmt.value.elts):
                            self._assign_target(
                                te,
                                self._tainted(ve, tainted, producers),
                                tainted,
                            )
                    else:
                        self._assign_target(t, is_t, tainted)
            elif isinstance(stmt, ast.AnnAssign):
                check(stmt.value)
                if stmt.value is not None:
                    if self._is_jit_factory(stmt.value):
                        seg = _last_segment(stmt.target)
                        if seg is not None:
                            producers.add(seg)
                    else:
                        self._assign_target(
                            stmt.target,
                            self._tainted(stmt.value, tainted, producers),
                            tainted,
                        )
            elif isinstance(stmt, ast.AugAssign):
                check(stmt.value)
                if self._tainted(stmt.value, tainted, producers):
                    self._assign_target(stmt.target, True, tainted)
            elif isinstance(stmt, ast.Expr):
                check(stmt.value)
            elif isinstance(stmt, ast.Return):
                check(stmt.value)
            elif isinstance(stmt, ast.If):
                check(stmt.test)
                self._scan(stmt.body, tainted, producers, scopes, path, out)
                self._scan(
                    stmt.orelse, tainted, producers, scopes, path, out
                )
            elif isinstance(stmt, ast.Match):
                check(stmt.subject)
                # case patterns bind names from the (possibly tainted)
                # subject; taint them all — over-tainting a match arm
                # beats going blind inside it
                subject_tainted = self._tainted(
                    stmt.subject, tainted, producers
                )
                for case in stmt.cases:
                    for pname in ast.walk(case.pattern):
                        if isinstance(pname, (ast.MatchAs, ast.MatchStar)) \
                                and pname.name is not None:
                            if subject_tainted:
                                tainted.add(pname.name)
                    if case.guard is not None:
                        check(case.guard)
                    self._scan(
                        case.body, tainted, producers, scopes, path, out
                    )
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                check(stmt.iter)
                self._assign_target(
                    stmt.target,
                    self._tainted(stmt.iter, tainted, producers),
                    tainted,
                )
                self._scan(stmt.body, tainted, producers, scopes, path, out)
                self._scan(
                    stmt.orelse, tainted, producers, scopes, path, out
                )
            elif isinstance(stmt, ast.While):
                check(stmt.test)
                self._scan(stmt.body, tainted, producers, scopes, path, out)
                self._scan(
                    stmt.orelse, tainted, producers, scopes, path, out
                )
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    check(item.context_expr)
                    if item.optional_vars is not None:
                        self._assign_target(
                            item.optional_vars,
                            self._tainted(
                                item.context_expr, tainted, producers
                            ),
                            tainted,
                        )
                self._scan(stmt.body, tainted, producers, scopes, path, out)
            elif isinstance(stmt, ast.Try):
                self._scan(stmt.body, tainted, producers, scopes, path, out)
                for handler in stmt.handlers:
                    self._scan(
                        handler.body, tainted, producers, scopes, path, out
                    )
                self._scan(
                    stmt.orelse, tainted, producers, scopes, path, out
                )
                self._scan(
                    stmt.finalbody, tainted, producers, scopes, path, out
                )
            elif isinstance(stmt, (ast.Raise, ast.Assert)):
                for field in ast.iter_child_nodes(stmt):
                    check(field)
            elif isinstance(stmt, ast.Delete):
                for t in stmt.targets:
                    self._assign_target(t, False, tainted)
            # Import/Global/Nonlocal/Pass/Break/Continue: nothing to do

    def check(self, module: ModuleFile) -> List[Violation]:
        if not module.matches(self.scope):
            return []
        out: List[Violation] = []
        self._scan(
            module.tree.body, set(), set(self.producers), [],
            module.path, out,
        )
        return out
