"""delta-parity: full and delta lowerings share the per-row helpers.

``lower_nodes_delta`` is bit-identical to ``lower_nodes`` *by
construction* only while both reach row values exclusively through the
shared per-row helper registry (``_node_metric_row``,
``_node_hold_rows``, ``_clip_i32``, ``resources_to_vector``). The
moment either path computes a row value inline — an arithmetic
expression, an ``np.array`` literal, an ``np.maximum``/``np.where``
fold — the two can drift without any test noticing until a churn tick
disagrees with a full relower. This rule bans inline value math in the
paired functions' bodies and requires every registered helper to be
called from both paths.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import List, Sequence, Set, Tuple

from koordinator_tpu.analysis.graftcheck.engine import (
    ModuleFile,
    Violation,
    attr_chain,
)

_ARITH_OPS = (
    ast.Add, ast.Sub, ast.Mult, ast.Div, ast.FloorDiv, ast.Mod, ast.Pow,
    ast.MatMult,
)
#: numpy value-construction/folding calls that belong in helpers, never
#: inline in a parity-coupled path
_BANNED_NP = ("array", "maximum", "minimum", "where", "clip", "stack")


@dataclasses.dataclass(frozen=True)
class ParitySpec:
    path: str                         # repo-relative module path (exact)
    funcs: Tuple[str, ...]            # parity-coupled lowering functions
    required_helpers: Tuple[str, ...]  # must be called from EVERY path
    allowed_helpers: Tuple[str, ...] = ()


class DeltaParityRule:
    name = "delta-parity"
    description = (
        "the delta/full lowering pair reaches row values only through "
        "the shared per-row helper registry"
    )

    def __init__(self, specs: Sequence[ParitySpec]):
        self.specs = tuple(specs)

    def _check_func(self, fn: ast.FunctionDef, spec: ParitySpec,
                    path: str, out: List[Violation]) -> Set[str]:
        called: Set[str] = set()
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func) or ""
                seg = chain.split(".")[-1] if chain else None
                if seg in spec.required_helpers or \
                        seg in spec.allowed_helpers:
                    called.add(seg)
                root = chain.split(".")[0] if chain else ""
                if root in ("np", "numpy") and seg in _BANNED_NP:
                    out.append(Violation(
                        rule=self.name, path=path, line=node.lineno,
                        col=node.col_offset, func=fn.name,
                        symbol=chain,
                        message=(
                            f"inline {chain}() in parity-coupled "
                            f"{fn.name} — row construction/folding must "
                            f"live in a shared per-row helper"
                        ),
                    ))
            elif isinstance(node, ast.BinOp) and isinstance(
                node.op, _ARITH_OPS
            ):
                out.append(Violation(
                    rule=self.name, path=path, line=node.lineno,
                    col=node.col_offset, func=fn.name,
                    symbol=type(node.op).__name__,
                    message=(
                        f"inline arithmetic "
                        f"`{ast.unparse(node)}` in parity-coupled "
                        f"{fn.name} — value math must live in a shared "
                        f"per-row helper"
                    ),
                ))
            elif isinstance(node, ast.AugAssign) and isinstance(
                node.op, _ARITH_OPS
            ):
                out.append(Violation(
                    rule=self.name, path=path, line=node.lineno,
                    col=node.col_offset, func=fn.name,
                    symbol=type(node.op).__name__,
                    message=(
                        f"inline augmented arithmetic "
                        f"`{ast.unparse(node)}` in parity-coupled "
                        f"{fn.name} — value math must live in a shared "
                        f"per-row helper"
                    ),
                ))
        return called

    def check(self, module: ModuleFile) -> List[Violation]:
        out: List[Violation] = []
        for spec in self.specs:
            if module.path != spec.path:
                continue
            found = {}
            for node in module.tree.body:
                if isinstance(node, ast.FunctionDef) and \
                        node.name in spec.funcs:
                    found[node.name] = node
            for name in spec.funcs:
                if name not in found:
                    out.append(Violation(
                        rule=self.name, path=module.path, line=1, col=0,
                        func="<module>", symbol=name,
                        message=(
                            f"parity-coupled function {name} not found "
                            f"at module top level"
                        ),
                    ))
            if len(found) != len(spec.funcs):
                continue
            called = {
                name: self._check_func(found[name], spec, module.path, out)
                for name in spec.funcs
            }
            for helper in spec.required_helpers:
                for name in spec.funcs:
                    if helper not in called[name]:
                        out.append(Violation(
                            rule=self.name, path=module.path,
                            line=found[name].lineno, col=0, func=name,
                            symbol=helper,
                            message=(
                                f"{name} does not call shared per-row "
                                f"helper {helper} — the delta/full pair "
                                f"must route rows through the same "
                                f"registry"
                            ),
                        ))
        return out
