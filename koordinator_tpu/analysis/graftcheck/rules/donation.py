"""donation-safety: a donated buffer must be provably dead afterwards.

``donate_argnums`` is the staging path's double-buffering lever (PR 6)
— and its sharpest knife. XLA reuses the donated buffer for the
output, so ANY later read of the donated value reads clobbered memory:
exactly the PR 11 scatter-clobber (the pipelined prestage donated the
staged generation a dispatched-but-unretired solve was still reading;
fixed by hand with ``scatter_node_rows_copied`` + pin bookkeeping).
This rule turns that fix into a machine-checked invariant:

1. **Donating callables** are discovered repo-wide from the binding
   idiom: ``X = jax.jit(f, donate_argnums=(...))`` (non-empty), bare or
   wrapped (``DEVICE_OBS.jit("name", jax.jit(f, donate_argnums=...))``),
   module-level or ``self.X = ...``, plus ``@partial(jax.jit,
   donate_argnums=...)`` decorators.
2. **Liveness**: at every call site of a donating callable, each
   donated positional argument that names a value (``x`` /
   ``self.attr``) must be dead after the call — the call's own
   statement reassigns it, or no later statement (straight-line
   suffix, enclosing blocks, loop wrap-around) reads it before a
   reassignment.
3. **Pin guards** (:class:`PinSpec`): an attribute that participates
   in a pin protocol (``StagedStateCache.state`` vs ``_pinned``) may
   only be donated inside a branch that proved ``attr is not pinned``
   — the un-guarded donation IS the PR 11 bug shape, flagged even
   though the attr is immediately reassigned.

Complex-expression arguments (a temporary like ``donated(f(x), ...)``)
are dead by construction and skipped. The analysis under-reports
(reads hidden behind aliases or escapes into containers are not
tracked); what it does flag is mechanically a use-after-free on
device memory.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from koordinator_tpu.analysis.graftcheck.engine import (
    ModuleFile,
    Violation,
    attr_chain,
)
from koordinator_tpu.analysis.graftcheck.callgraph import Program


@dataclasses.dataclass(frozen=True)
class PinSpec:
    """An attribute under a pin protocol: donating ``self.<attr>`` in
    ``class_name`` requires an enclosing ``<attr> is/is not <pin_attr>``
    guard proving the generation is not pinned."""

    path: str
    class_name: str
    attr: str          # e.g. "state"
    pin_attr: str      # e.g. "_pinned"


def _jit_donate_argnums(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Non-empty donate_argnums of a (possibly wrapped) jit factory
    call, else None."""
    chain = attr_chain(call.func) or ""
    seg = chain.split(".")[-1] if chain else ""
    if seg in ("jit", "pjit"):
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                nums = _int_tuple(kw.value)
                if nums:
                    return nums
        # wrapped: the declaration may live on an inner factory arg
        for a in call.args:
            if isinstance(a, ast.Call):
                inner = _jit_donate_argnums(a)
                if inner:
                    return inner
        return None
    if seg == "partial" and call.args:
        head = attr_chain(call.args[0]) or ""
        if head.split(".")[-1] in ("jit", "pjit"):
            for kw in call.keywords:
                if kw.arg == "donate_argnums":
                    return _int_tuple(kw.value)
    return None


def _int_tuple(node: ast.AST) -> Optional[Tuple[int, ...]]:
    if isinstance(node, (ast.Tuple, ast.List)):
        out = []
        for e in node.elts:
            if isinstance(e, ast.Constant) and isinstance(e.value, int):
                out.append(e.value)
            else:
                return None
        return tuple(out)
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return (node.value,)
    return None


def _target_chain(node: ast.AST) -> Optional[str]:
    """A donated argument worth tracking: a bare name or a self-attr
    chain (``x``, ``self.state``). Anything else is a temporary."""
    if isinstance(node, ast.Name):
        return node.id
    chain = attr_chain(node)
    if chain is not None and chain.startswith("self."):
        return chain
    return None


def _reads(node: ast.AST, chain: str) -> Optional[ast.AST]:
    """First read of ``chain`` anywhere under ``node`` (load context;
    an exact-store is not a read, but a read of a longer chain rooted
    at it — ``self.state.alloc`` after donating ``self.state`` — is)."""
    parts = chain.split(".")
    for sub in ast.walk(node):
        got = None
        if isinstance(sub, ast.Name) and sub.id == parts[0] \
                and len(parts) == 1:
            got = sub
        elif isinstance(sub, ast.Attribute):
            sub_chain = attr_chain(sub)
            if sub_chain == chain:
                got = sub
        if got is not None and not isinstance(
            getattr(got, "ctx", None), ast.Store
        ):
            return got
    return None


def _kills(stmt: ast.stmt, chain: str) -> bool:
    """Whether this statement unconditionally reassigns ``chain``."""
    targets: List[ast.AST] = []
    if isinstance(stmt, ast.Assign):
        targets = stmt.targets
    elif isinstance(stmt, (ast.AnnAssign, ast.AugAssign)):
        targets = [stmt.target] if isinstance(stmt, ast.AnnAssign) \
            else []  # aug-assign READS then writes — not a kill
    elif isinstance(stmt, ast.Delete):
        targets = stmt.targets
    for t in targets:
        if isinstance(t, ast.Name) and t.id == chain:
            return True
        if isinstance(t, ast.Attribute) and attr_chain(t) == chain:
            return True
    return False


class DonationRule:
    name = "donation-safety"
    description = (
        "a value passed to a donate_argnums jit is dead afterwards: no "
        "later read, no donation of a possibly-pinned generation"
    )

    def __init__(self, pin_specs: Sequence[PinSpec] = (),
                 no_donate_globs: Sequence[str] = ()):
        self.pin_specs = tuple(pin_specs)
        #: modules (fnmatch globs) whose jit factories must declare
        #: EMPTY donation — the warm pool's program constructors.
        #: DESIGN §19.2: a donated jit replayed from a persistent
        #: store mis-applies its alias map on this jax line, so "the
        #: warm path never donates" is a machine invariant here, not a
        #: convention. The companion adopt-site check below guards the
        #: other door: a donating binding can never be ADOPTED into
        #: the pool from any module.
        self.no_donate_globs = tuple(no_donate_globs)

    # -- discovery -----------------------------------------------------------

    def _donating_names(self, program: Program) -> Dict[str, Tuple[int, ...]]:
        """Binding name (last segment) -> donated argnums, repo-wide."""
        out: Dict[str, Tuple[int, ...]] = {}
        for module in program.modules:
            for node in ast.walk(module.tree):
                if isinstance(node, ast.Assign) \
                        and isinstance(node.value, ast.Call):
                    nums = _jit_donate_argnums(node.value)
                    if not nums:
                        continue
                    for t in node.targets:
                        seg = (
                            t.attr if isinstance(t, ast.Attribute)
                            else t.id if isinstance(t, ast.Name)
                            else None
                        )
                        if seg is not None:
                            out[seg] = nums
                elif isinstance(node, (ast.FunctionDef,
                                       ast.AsyncFunctionDef)):
                    for dec in node.decorator_list:
                        if isinstance(dec, ast.Call):
                            nums = _jit_donate_argnums(dec)
                            if nums:
                                out[node.name] = nums
        return out

    # -- per-call-site checks ------------------------------------------------

    def check_program(self, program: Program) -> List[Violation]:
        donating = self._donating_names(program)
        if not donating and not self.no_donate_globs:
            # nothing to check: no donating bindings anywhere and no
            # warm-path modules configured (the declaration check is
            # the one pass that must run on an empty donating map)
            return []
        out: List[Violation] = []
        for module in program.modules:
            out.extend(self._check_module(module, donating))
        return out

    def check(self, module: ModuleFile) -> List[Violation]:
        return self.check_program(Program([module]))

    def _check_module(self, module: ModuleFile,
                      donating: Dict[str, Tuple[int, ...]]
                      ) -> List[Violation]:
        out: List[Violation] = []
        self._check_warm_path(module, donating, out)

        def visit_fn(fn: ast.AST, qualname: str,
                     class_name: Optional[str]) -> None:
            for stmt_path, stmt, call in _donation_calls(fn, donating):
                nums = donating[_last_seg(call.func)]
                for idx in nums:
                    if idx >= len(call.args):
                        continue
                    arg = call.args[idx]
                    chain = _target_chain(arg)
                    if chain is None:
                        continue
                    self._check_liveness(
                        module, qualname, fn, stmt_path, stmt, call,
                        arg, chain, out,
                    )
                    pin_target = chain
                    if "." not in chain:
                        aliased = _resolve_alias(stmt_path, stmt, chain)
                        if aliased is not None:
                            pin_target = aliased
                    self._check_pin_guard(
                        module, qualname, class_name, fn, call, arg,
                        pin_target, out,
                    )

        _walk_functions(module.tree, [], None, visit_fn)
        return out

    # -- the warm path never donates (DESIGN §19.2 / §21) --------------------

    def _check_warm_path(self, module: ModuleFile,
                         donating: Dict[str, Tuple[int, ...]],
                         out: List[Violation]) -> None:
        import fnmatch

        in_scope = any(
            fnmatch.fnmatch(module.path, g) for g in self.no_donate_globs
        )
        for node in ast.walk(module.tree):
            if not isinstance(node, ast.Call):
                continue
            chain = attr_chain(node.func) or ""
            seg = chain.split(".")[-1] if chain else ""
            if in_scope and seg in ("jit", "pjit"):
                declared = None
                for kw in node.keywords:
                    if kw.arg == "donate_argnums":
                        # _int_tuple returns () for an empty literal,
                        # None for anything non-literal
                        declared = _int_tuple(kw.value)
                if declared != ():
                    out.append(Violation(
                        rule=self.name, path=module.path,
                        line=node.lineno, col=node.col_offset,
                        func="<module>", symbol=seg,
                        message=(
                            "warm-path jit factory must declare "
                            "donate_argnums=() — a donated executable "
                            "replayed from the store mis-aliases its "
                            "outputs (DESIGN §19.2; the warm pool "
                            "never donates)"
                        ),
                    ))
            if seg == "adopt" and node.args:
                # the other door: no donating binding may be ADOPTED
                # into the warm pool, from any module. The first
                # positional arg names the binding; resolve it against
                # the repo-wide donating-names map.
                arg0 = node.args[0]
                name = None
                if isinstance(arg0, ast.Name):
                    name = arg0.id
                elif isinstance(arg0, ast.Attribute):
                    name = arg0.attr
                if name is not None and name in donating:
                    out.append(Violation(
                        rule=self.name, path=module.path,
                        line=node.lineno, col=node.col_offset,
                        func="<module>", symbol=name,
                        message=(
                            f"{name} donates "
                            f"(donate_argnums={donating[name]}) and is "
                            f"adopted into the warm pool — restored "
                            f"replays of donated programs mis-alias "
                            f"their outputs (DESIGN §19.2); adopt only "
                            f"non-donating twins"
                        ),
                    ))

    def _check_liveness(self, module: ModuleFile, qualname: str,
                        fn: ast.AST, stmt_path: List[List[ast.stmt]],
                        stmt: ast.stmt, call: ast.Call, arg: ast.AST,
                        chain: str, out: List[Violation]) -> None:
        if _kills(stmt, chain):
            # `x = donated(x, ...)`: the canonical safe shape — the
            # binding is reassigned by the very statement that donates,
            # so every later read sees the fresh output buffer
            return
        read = None
        kill_depth = None  # stmt_path index of the block a kill lives in
        # 1. straight-line suffix: siblings after the call's statement,
        #    then the statements after each enclosing block — in
        #    program order, stopping at a reassignment (reads are
        #    checked FIRST: `x = f(x)` both kills and reads, and the
        #    read is of the clobbered buffer)
        for depth in range(len(stmt_path) - 1, -1, -1):
            block = stmt_path[depth]
            anchor = block.index(_containing(block, stmt))
            for later in block[anchor + 1:]:
                read = _reads(later, chain)
                if read is not None:
                    break
                if _kills(later, chain):
                    kill_depth = depth
                    break
            if read is not None or kill_depth is not None:
                break
        if read is None:
            # 2. loop wrap-around: the statements from the top of an
            #    enclosing loop body down to the call re-run next
            #    iteration with the donated buffer still bound. A
            #    downstream kill only launders a loop's wrap-around if
            #    it happens INSIDE that loop's body (a kill after the
            #    loop exits never runs between iterations); a
            #    reassignment at the top of the body launders what
            #    follows it
            for block, _loop in _enclosing_loops(fn, stmt):
                loop_depth = next(
                    (i for i, b in enumerate(stmt_path) if b is block),
                    None,
                )
                if kill_depth is not None and loop_depth is not None \
                        and kill_depth >= loop_depth:
                    continue  # killed before this loop's body ends
                anchor_stmt = _containing(block, stmt)
                for earlier in block:
                    # the anchor itself re-runs too: donating the same
                    # un-reassigned binding next iteration reads (and
                    # re-donates) an already-clobbered buffer
                    read = _reads(earlier, chain)
                    if read is not None:
                        break
                    if earlier is anchor_stmt or _kills(earlier, chain):
                        break
                if read is not None:
                    break
        if read is not None:
            out.append(Violation(
                rule=self.name, path=module.path,
                line=read.lineno, col=read.col_offset, func=qualname,
                symbol=chain,
                message=(
                    f"{chain} read after being donated to "
                    f"{_last_seg(call.func)}() at line {call.lineno} — "
                    f"the buffer is clobbered by XLA (use the copied "
                    f"variant or reassign before reading)"
                ),
            ))

    def _check_pin_guard(self, module: ModuleFile, qualname: str,
                         class_name: Optional[str], fn: ast.AST,
                         call: ast.Call, arg: ast.AST, chain: str,
                         out: List[Violation]) -> None:
        spec = None
        for s in self.pin_specs:
            if s.path == module.path and s.class_name == class_name \
                    and chain == f"self.{s.attr}":
                spec = s
                break
        if spec is None:
            return
        if self._pin_guarded(fn, call, chain, f"self.{spec.pin_attr}"):
            return
        out.append(Violation(
            rule=self.name, path=module.path, line=call.lineno,
            col=call.col_offset, func=qualname, symbol=chain,
            message=(
                f"{chain} donated without a `{chain} is not "
                f"self.{spec.pin_attr}` guard — a pinned in-flight "
                f"generation would be clobbered under the dispatch "
                f"(the PR 11 scatter-clobber shape)"
            ),
        ))

    @staticmethod
    def _pin_guarded(fn: ast.AST, call: ast.Call, chain: str,
                     pin_chain: str) -> bool:
        """Whether ``call`` sits in the not-pinned branch of an
        ``<chain> is/is not <pin_chain>`` test. Boolean combinations
        keep only the SOUND direction: the orelse of ``if (X is PIN)
        or C`` proves ``X is not PIN`` (every disjunct is false
        there), and the body of ``if (X is not PIN) and C`` proves it
        too (every conjunct holds there) — the dual placements prove
        nothing and stay unguarded."""

        def bare_compare(test: ast.expr) -> Optional[str]:
            if not (isinstance(test, ast.Compare)
                    and len(test.ops) == 1
                    and len(test.comparators) == 1):
                return None
            sides = {attr_chain(test.left),
                     attr_chain(test.comparators[0])}
            if sides != {chain, pin_chain}:
                return None
            return "is" if isinstance(test.ops[0], ast.Is) else \
                "is-not" if isinstance(test.ops[0], ast.IsNot) else None

        def compare_matches(test: ast.expr) -> Optional[str]:
            direct = bare_compare(test)
            if direct is not None:
                return direct
            if isinstance(test, ast.BoolOp):
                kinds = [bare_compare(v) for v in test.values]
                if isinstance(test.op, ast.Or) and "is" in kinds:
                    return "is"
                if isinstance(test.op, ast.And) and "is-not" in kinds:
                    return "is-not"
            return None

        def contains(node: ast.AST) -> bool:
            return any(sub is call for sub in ast.walk(node))

        def search(node: ast.AST) -> bool:
            for sub in ast.iter_child_nodes(node):
                if isinstance(sub, ast.If) and contains(sub):
                    op = compare_matches(sub.test)
                    if op == "is" and any(
                        contains(s) for s in sub.orelse
                    ):
                        return True
                    if op == "is-not" and any(
                        contains(s) for s in sub.body
                    ):
                        return True
                    if search(sub):
                        return True
                elif contains(sub):
                    return search(sub)
            return False

        return search(fn)


def _resolve_alias(stmt_path: List[List[ast.stmt]], stmt: ast.stmt,
                   chain: str) -> Optional[str]:
    """The self-attr a bare donated name was just bound from: ``cur =
    self.state`` dominating the donation in the same block makes
    ``cur`` an alias of ``self.state``. The pin protocol follows the
    GENERATION, not the binding — the staging wrappers capture a local
    precisely so their dispatch closures never read ``self`` state,
    and without this resolution that capture would hide the PR 11
    unguarded-donation shape from the rule."""
    block = stmt_path[-1]
    anchor = block.index(_containing(block, stmt))
    for earlier in reversed(block[:anchor]):
        if isinstance(earlier, ast.Assign) \
                and len(earlier.targets) == 1 \
                and isinstance(earlier.targets[0], ast.Name) \
                and earlier.targets[0].id == chain:
            value_chain = attr_chain(earlier.value)
            if value_chain is not None \
                    and value_chain.startswith("self."):
                return value_chain
            return None
        if _kills(earlier, chain):
            return None
    return None


def _last_seg(func: ast.AST) -> str:
    chain = attr_chain(func) or ""
    return chain.split(".")[-1] if chain else ""


def _containing(block: List[ast.stmt], stmt: ast.stmt) -> ast.stmt:
    """The statement in ``block`` that contains (or is) ``stmt``."""
    for s in block:
        if s is stmt or any(sub is stmt for sub in ast.walk(s)):
            return s
    return stmt


def _donation_calls(fn: ast.AST, donating: Dict[str, Tuple[int, ...]]):
    """(enclosing block chain, statement, call) for every donating-
    callable call in ``fn``, nested defs excluded."""
    results = []

    def walk(body: List[ast.stmt], path: List[List[ast.stmt]]) -> None:
        for stmt in body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                continue
            for sub in ast.walk(stmt):
                if isinstance(sub, ast.Call) \
                        and _last_seg(sub.func) in donating:
                    results.append((path + [body], stmt, sub))
            for child_body in _child_blocks(stmt):
                walk(child_body, path + [body])

    # dedupe: ast.walk above re-finds calls inside child blocks; keep
    # the DEEPEST (most precise) block chain per call node
    walk(fn.body, [])
    best: Dict[int, Tuple] = {}
    for path, stmt, call in results:
        cur = best.get(id(call))
        if cur is None or len(path) > len(cur[0]):
            # prefer the entry whose statement list directly holds the
            # statement (deepest path)
            best[id(call)] = (path, stmt, call)
    # re-anchor stmt to the directly-enclosing statement of the deepest
    # block
    out = []
    for path, stmt, call in best.values():
        block = path[-1]
        out.append((path, _containing(block, call), call))
    return out


def _child_blocks(stmt: ast.stmt) -> List[List[ast.stmt]]:
    blocks: List[List[ast.stmt]] = []
    for field in ("body", "orelse", "finalbody"):
        val = getattr(stmt, field, None)
        if isinstance(val, list) and val \
                and isinstance(val[0], ast.stmt):
            blocks.append(val)
    for handler in getattr(stmt, "handlers", []) or []:
        blocks.append(handler.body)
    for case in getattr(stmt, "cases", []) or []:
        blocks.append(case.body)
    return blocks


def _enclosing_loops(fn: ast.AST, stmt: ast.stmt):
    """(loop body, loop node) for every loop enclosing ``stmt``."""
    out = []

    def walk(node: ast.AST) -> bool:
        found = node is stmt
        for child in ast.iter_child_nodes(node):
            if walk(child):
                found = True
        if found and isinstance(node, (ast.For, ast.AsyncFor,
                                       ast.While)):
            out.append((node.body, node))
        return found

    walk(fn)
    return out


def _walk_functions(tree: ast.Module, scopes: List[str],
                    class_name: Optional[str], visit) -> None:
    _walk_fn_stmts(tree.body, scopes, class_name, visit)


def _walk_fn_stmts(body: List[ast.stmt], scopes: List[str],
                   class_name: Optional[str], visit) -> None:
    for stmt in body:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            qual = ".".join(scopes + [stmt.name])
            visit(stmt, qual, class_name)
            _walk_fn_stmts(stmt.body, scopes + [stmt.name], class_name,
                           visit)
        elif isinstance(stmt, ast.ClassDef):
            _walk_fn_stmts(stmt.body, scopes + [stmt.name], stmt.name
                           if class_name is None else class_name, visit)
        else:
            for child_body in _child_blocks(stmt):
                _walk_fn_stmts(child_body, scopes, class_name, visit)
