"""lock-discipline: mapped mutable attributes only touched under their lock.

The incremental staging path (PR 6) hinges on three classes staying
race-free: ``SchedulerCache`` (informer mutations vs snapshot capture),
``ClusterDeltaTracker`` (mark epochs vs ``dirty_since``), and
``StagedStateCache`` (host/device halves patched between solves). Each
declares an attribute→lock map here; any read or write of a mapped
attribute outside a ``with self.<lock>:`` block — in the class's own
methods — is a violation. ``__init__`` is exempt (no concurrent aliases
exist during construction). The map is deliberately class-internal:
state callers need atomically is returned from inside the lock hold
that produced it (``StagedStateCache.ensure``'s trailing (epoch, delta)
pair), and keeping mapped attributes out of other modules' code paths
remains a review duty (not machine-checked — see docs/DESIGN.md §11).
"""

from __future__ import annotations

import ast
import dataclasses
from typing import List, Sequence, Tuple

from koordinator_tpu.analysis.graftcheck.engine import ModuleFile, Violation


@dataclasses.dataclass(frozen=True)
class LockSpec:
    path: str                  # repo-relative module path (exact)
    class_name: str
    lock: str                  # e.g. "_lock"
    attrs: Tuple[str, ...]     # mutable attributes guarded by the lock
    exempt_methods: Tuple[str, ...] = ("__init__",)


class LockDisciplineRule:
    name = "lock-discipline"
    description = (
        "mapped mutable attributes of concurrency-critical classes are "
        "only read/written inside `with self.<lock>` blocks"
    )

    def __init__(self, specs: Sequence[LockSpec]):
        self.specs = tuple(specs)

    def _is_lock_ctx(self, expr: ast.expr, lock: str) -> bool:
        return (
            isinstance(expr, ast.Attribute)
            and expr.attr == lock
            and isinstance(expr.value, ast.Name)
            and expr.value.id == "self"
        )

    def _walk(self, node: ast.AST, spec: LockSpec, locked: bool,
              method: str, path: str, out: List[Violation]) -> None:
        if isinstance(node, ast.With):
            holds = locked or any(
                self._is_lock_ctx(item.context_expr, spec.lock)
                for item in node.items
            )
            for item in node.items:
                self._walk(
                    item.context_expr, spec, locked, method, path, out
                )
            for stmt in node.body:
                self._walk(stmt, spec, holds, method, path, out)
            return
        if isinstance(node, ast.Attribute):
            if (
                isinstance(node.value, ast.Name)
                and node.value.id == "self"
                and node.attr in spec.attrs
                and not locked
            ):
                out.append(Violation(
                    rule=self.name, path=path, line=node.lineno,
                    col=node.col_offset,
                    func=f"{spec.class_name}.{method}",
                    symbol=f"self.{node.attr}",
                    message=(
                        f"self.{node.attr} touched outside "
                        f"`with self.{spec.lock}` (maps to "
                        f"{spec.class_name}.{spec.lock})"
                    ),
                ))
        # nested defs run later, possibly without the lock held — treat
        # their bodies as unlocked unless they re-acquire
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            body = node.body if isinstance(node.body, list) else [node.body]
            for child in body:
                self._walk(child, spec, False, method, path, out)
            return
        for child in ast.iter_child_nodes(node):
            self._walk(child, spec, locked, method, path, out)

    def check(self, module: ModuleFile) -> List[Violation]:
        out: List[Violation] = []
        for spec in self.specs:
            if module.path != spec.path:
                continue
            for node in ast.walk(module.tree):
                if not (
                    isinstance(node, ast.ClassDef)
                    and node.name == spec.class_name
                ):
                    continue
                for item in node.body:
                    if not isinstance(
                        item, (ast.FunctionDef, ast.AsyncFunctionDef)
                    ):
                        continue
                    if item.name in spec.exempt_methods:
                        continue
                    for stmt in item.body:
                        self._walk(
                            stmt, spec, False, item.name, module.path, out
                        )
        return out
