"""graftcheck v3: the three shape-flow passes (docs/DESIGN.md §23).

Built on :mod:`..shapeflow` (the lattice/engine half) and the v2 call
graph. Together they turn "we pre-warm every quarter-pow2 bucket and
count recompiles" (PRs 13/17/18's *empirical* defenses) into a static
proof obligation:

1. **bucket-flow** — no raw-dynamic count (``len()``, comprehension,
   arithmetic-derived) reaches a host-side device-width sink without
   passing through the registered bucket family. The pre-PR 8 / pre-PR
   16 storm shape, machine-rejected.
2. **signature-space** — every ``DEVICE_OBS.jit`` binding carries a
   declared axis spec whose bucket functions are evaluated over the
   documented config bounds to a FINITE image; the enumerated space is
   emitted as a machine-readable sidecar (``--format=json`` gains
   ``signature_space``) and feeds the runtime sentinel
   (testing/shapeflow.py). An undeclared binding is an unknown
   recompile surface and fails loudly, as does a stale declaration.
3. **warm-coverage** — every WARM_POOL-adopted binding's enumerated
   space must be representable by ``warm_manifest()`` keys: statics by
   value (declared hashable), arrays as ShapeDtypeStructs (finite
   enumeration). The inverse holds too: a hot-module ``DEVICE_OBS``
   binding that is NOT adopted is cold on every recovery path and gets
   a loud finding (allowlistable with a written reason — e.g. the
   sharded bindings, which the single-device pool refuses by design).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Tuple

from koordinator_tpu.analysis.graftcheck.callgraph import Program
from koordinator_tpu.analysis.graftcheck.engine import (
    ModuleFile,
    Violation,
    module_matches,
)
from koordinator_tpu.analysis.graftcheck.shapeflow import (
    BucketFn,
    ShapeFlowEngine,
    find_adoptions,
    find_observed_bindings,
)


# -- pass 1: bucket-flow -----------------------------------------------------

class BucketFlowRule:
    """Whole-program: raw-dynamic counts never reach device-width
    sinks outside the bucket family (see shapeflow.py for the lattice
    and the sink set)."""

    name = "bucket-flow"
    description = (
        "every dynamic count feeding a jit-visible axis flows through "
        "a registered bucket function (interprocedural shape-flow)"
    )

    def __init__(self, scope: Sequence[str], buckets: Sequence[BucketFn]):
        self.scope = tuple(scope)
        self.buckets = tuple(buckets)

    def check_program(self, program: Program) -> List[Violation]:
        # the fixpoint runs at construction — memoize per Program +
        # bucket registry like the binding census
        cached = getattr(program, "_shapeflow_engine", None)
        if cached is not None and cached[0] == self.buckets:
            engine = cached[1]
        else:
            engine = ShapeFlowEngine(program, self.buckets)
            program._shapeflow_engine = (self.buckets, engine)
        out = []
        for path, line, col, qual, symbol, message in \
                engine.violations(self.scope):
            out.append(Violation(
                rule=self.name, path=path, line=line, col=col,
                func=qual, symbol=symbol, message=message,
            ))
        return out

    def check(self, module: ModuleFile) -> List[Violation]:
        return self.check_program(Program([module]))


# -- pass 2: signature-space enumeration -------------------------------------

@dataclasses.dataclass(frozen=True)
class AxisSpec:
    """One dynamic axis of a jit binding's signature space.

    ``bucket`` is a ``"dotted.module:qual.name"`` reference to the
    sanctioning bucket callable — imported and EVALUATED over
    ``range(bound + 1)`` (per kwargs option) so the enumerated image is
    the real function's, never a hand-copied table. An axis with no
    bucket (``bucket=""``) is a config-capped raw axis: every integer
    in ``[1, bound]`` is reachable (the admission gate's lane count);
    finite because the bound is a config cap, not a bucket image."""

    axis: str
    bucket: str = ""
    #: kwargs options swept and unioned, e.g. ((("floor", 64),),) or
    #: ((("shards", 1),), (("shards", 8),))
    kwargs_options: Tuple[Tuple[Tuple[str, int], ...], ...] = ((),)
    bound: int = 0
    bound_source: str = ""


@dataclasses.dataclass(frozen=True)
class BindingSpec:
    """The declared signature space of one ``DEVICE_OBS.jit`` binding.

    ``structural`` names the quasi-static axes (node width, feature
    columns) that change only on structure epochs — they contribute
    one value per deployment shape, not a per-tick surface, and the
    runtime sentinel checks them as constant-within-window instead of
    bucket-image members."""

    name: str
    path: str
    axes: Tuple[AxisSpec, ...]
    structural: Tuple[str, ...] = ()
    note: str = ""


def _resolve_bucket(ref: str):
    """``"pkg.mod:Qual.name"`` -> the live callable (images must come
    from the real function, not a parallel reimplementation)."""
    import importlib

    mod_name, _, qual = ref.partition(":")
    obj = importlib.import_module(mod_name)
    for part in qual.split("."):
        obj = getattr(obj, part)
    return obj


#: enumerated images cached per (bucket ref, kwargs, bound) — the
#: bucket family is shared across bindings, so a repo run evaluates
#: each distinct (fn, kwargs, bound) once
_IMAGE_CACHE: Dict[Tuple, Tuple[int, ...]] = {}


def enumerate_axis(spec: AxisSpec) -> Tuple[int, ...]:
    """The axis's reachable value set under its bound (sorted)."""
    if not spec.bucket:
        return tuple(range(1, spec.bound + 1))
    values: set = set()
    for opts in spec.kwargs_options:
        key = (spec.bucket, opts, spec.bound)
        cached = _IMAGE_CACHE.get(key)
        if cached is None:
            fn = _resolve_bucket(spec.bucket)
            kwargs = dict(opts)
            cached = tuple(sorted({
                int(fn(n, **kwargs)) for n in range(spec.bound + 1)
            }))
            _IMAGE_CACHE[key] = cached
        values.update(cached)
    return tuple(sorted(values))


class SignatureSpaceRule:
    """Whole-program: the ``DEVICE_OBS.jit`` binding census must match
    the declared axis-spec registry, and every declared axis must
    enumerate to a finite image under the documented bounds.

    After ``check_program`` runs, :attr:`last_space` holds the
    machine-readable sidecar (also exported by the CLI's JSON format
    and consumed by the runtime sentinel)."""

    name = "signature-space"
    description = (
        "every DEVICE_OBS-instrumented jit binding has a declared, "
        "finitely-enumerable signature space under the config bounds"
    )

    #: a bucket image larger than this is not a bucket, it is an
    #: unbounded surface wearing a bucket's name
    MAX_AXIS_IMAGE = 4096

    def __init__(self, specs: Sequence[BindingSpec],
                 obs_names: Sequence[str] = ("DEVICE_OBS",)):
        self.specs = tuple(specs)
        self.obs_names = tuple(obs_names)
        self.last_space: Dict[str, dict] = {}

    def check_program(self, program: Program) -> List[Violation]:
        out: List[Violation] = []
        bindings = find_observed_bindings(program, self.obs_names)
        adoptions = find_adoptions(program, bindings=bindings)
        adopted = {a.binding for a in adoptions if a.binding}
        by_name = {s.name: s for s in self.specs}
        seen = set()
        space: Dict[str, dict] = {}
        for b in bindings:
            seen.add(b.name)
            spec = by_name.get(b.name)
            if spec is None:
                out.append(Violation(
                    rule=self.name, path=b.path, line=b.line, col=0,
                    func=b.qualname, symbol=b.name,
                    message=(
                        f"DEVICE_OBS.jit binding {b.name!r} has no "
                        f"BindingSpec: an undeclared hot jit is an "
                        f"unknown recompile surface — declare its axis "
                        f"buckets (rules/__init__.BINDING_SPECS)"
                    ),
                ))
                continue
            axes = []
            bound_total = 1
            for axis in spec.axes:
                try:
                    image = enumerate_axis(axis)
                except Exception as e:
                    out.append(Violation(
                        rule=self.name, path=b.path, line=b.line, col=0,
                        func=b.qualname, symbol=b.name,
                        message=(
                            f"axis {axis.axis!r} of {b.name!r} failed "
                            f"to enumerate ({type(e).__name__}: {e}) — "
                            f"the bucket reference {axis.bucket!r} must "
                            f"resolve to the live bucket function"
                        ),
                    ))
                    continue
                if not image or len(image) > self.MAX_AXIS_IMAGE:
                    out.append(Violation(
                        rule=self.name, path=b.path, line=b.line, col=0,
                        func=b.qualname, symbol=b.name,
                        message=(
                            f"axis {axis.axis!r} of {b.name!r} "
                            f"enumerates to {len(image)} values under "
                            f"bound {axis.bound} — not a finite bucket "
                            f"image (cap {self.MAX_AXIS_IMAGE})"
                        ),
                    ))
                    continue
                bound_total *= len(image)
                axes.append({
                    "axis": axis.axis,
                    "bucket": axis.bucket,
                    "bound": axis.bound,
                    "bound_source": axis.bound_source,
                    "image_size": len(image),
                    "values": list(image),
                })
            space[b.name] = {
                "path": b.path,
                "line": b.line,
                "adopted": b.name in adopted,
                "structural_axes": list(spec.structural),
                "axes": axes,
                "signature_space_bound": bound_total,
                "note": spec.note,
            }
        for spec in self.specs:
            if spec.name not in seen:
                out.append(Violation(
                    rule=self.name, path=spec.path, line=0, col=0,
                    func="<registry>", symbol=spec.name,
                    message=(
                        f"BindingSpec {spec.name!r} matches no "
                        f"DEVICE_OBS.jit binding in the program — "
                        f"delete the stale declaration"
                    ),
                ))
        self.last_space = space
        return out

    def check(self, module: ModuleFile) -> List[Violation]:
        return self.check_program(Program([module]))


# -- pass 3: warm-coverage ---------------------------------------------------

class WarmCoverageRule:
    """Whole-program: adopted bindings are warm-representable, and hot
    bindings are adopted (or loudly excused)."""

    name = "warm-coverage"
    description = (
        "every warm-pool-adopted binding's signature space is "
        "manifest-representable; every hot DEVICE_OBS binding is "
        "adopted or justified (cold-on-every-recovery otherwise)"
    )

    def __init__(self, specs: Sequence[BindingSpec],
                 hot_scope: Sequence[str],
                 hashable_statics: Sequence[str] = ("config",),
                 obs_names: Sequence[str] = ("DEVICE_OBS",)):
        self.specs = tuple(specs)
        self.hot_scope = tuple(hot_scope)
        self.hashable_statics = frozenset(hashable_statics)
        self.obs_names = tuple(obs_names)

    def check_program(self, program: Program) -> List[Violation]:
        out: List[Violation] = []
        bindings = find_observed_bindings(program, self.obs_names)
        by_target = {b.name: b for b in bindings}
        adoptions = find_adoptions(program, bindings=bindings)
        by_spec = {s.name: s for s in self.specs}
        adopted = set()
        for a in adoptions:
            if not a.binding:
                out.append(Violation(
                    rule=self.name, path=a.path, line=a.line, col=0,
                    func="<module>", symbol=a.target,
                    message=(
                        f"WARM_POOL.adopt target {a.target!r} does not "
                        f"resolve to a DEVICE_OBS.jit binding in this "
                        f"module — the coverage contract cannot be "
                        f"checked for an opaque adoption"
                    ),
                ))
                continue
            adopted.add(a.binding)
            b = by_target.get(a.binding)
            spec = by_spec.get(a.binding)
            if b is None:
                continue
            # statics by value: the manifest keys hash static config
            # values — an adopted binding may only declare statics the
            # registry knows to be hashable-by-value
            bad_statics = set(b.static_argnames) - self.hashable_statics
            if bad_statics or b.has_static_argnums:
                what = sorted(bad_statics) if bad_statics \
                    else "positional static_argnums"
                out.append(Violation(
                    rule=self.name, path=a.path, line=a.line, col=0,
                    func="<module>", symbol=a.binding,
                    message=(
                        f"adopted binding {a.binding!r} declares "
                        f"statics {what} outside the hashable-statics "
                        f"registry — warm_manifest() keys statics by "
                        f"value, so an unhashable/undeclared static is "
                        f"unrepresentable in the store"
                    ),
                ))
            if spec is None or not spec.axes:
                out.append(Violation(
                    rule=self.name, path=a.path, line=a.line, col=0,
                    func="<module>", symbol=a.binding,
                    message=(
                        f"adopted binding {a.binding!r} has no "
                        f"finitely-enumerated BindingSpec axes — the "
                        f"warm manifest cannot cover an unbounded "
                        f"signature space"
                    ),
                ))
        # the inverse: a hot binding that is NOT adopted restarts cold
        # on every recovery path (boot, promotion, respawn, failover)
        for b in bindings:
            if b.name in adopted:
                continue
            if not module_matches(b.path, self.hot_scope):
                continue
            out.append(Violation(
                rule=self.name, path=b.path, line=b.line, col=0,
                func=b.qualname, symbol=b.name,
                message=(
                    f"hot DEVICE_OBS.jit binding {b.name!r} is not "
                    f"warm-pool-adopted: cold-on-every-recovery — "
                    f"every restart/promotion/failover re-traces and "
                    f"recompiles it (adopt it, or allowlist with the "
                    f"reason it cannot be pooled)"
                ),
            ))
        return out

    def check(self, module: ModuleFile) -> List[Violation]:
        return self.check_program(Program([module]))
