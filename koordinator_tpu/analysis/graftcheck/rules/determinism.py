"""determinism-taint: nondeterminism must never reach device or wire.

The whole verification story — oracle bit-parity, chaos runs ending
bit-identical to fault-free runs, the sidecar solving byte-identically
to in-process — rests on the solve being a pure function of its typed
inputs. Wall clock (``time.time``), unseeded RNGs (``random.*``,
``os.urandom``, unseeded ``random.Random()``/``np.random.default_rng()``),
``uuid.uuid4``, and set iteration order (hash-seed dependent) are all
fine in telemetry — and poison in anything the parity tests compare.

This rule runs a local taint analysis (the host-sync rule's shape) over
the scoped modules:

- **sources**: wall-clock/monotonic reads, unseeded RNG draws,
  ``os.urandom``/``uuid4``/``secrets``, and materializing a set's
  iteration order (``list(s)``/``tuple(s)``/comprehension over a
  set-typed value);
- **launder**: ``sorted()``, ``min``/``max``/``len``/``sum``/``any``/
  ``all`` (order-insensitive; device values here are integer
  arithmetic end to end, DESIGN.md §2), and seeding (``random.Random(
  seed)``, ``default_rng(seed)``);
- **sinks**: device staging (``jnp.*``, ``jax.device_put``, jitted
  producers discovered from ``X = jax.jit(...)`` bindings, the
  configured producer set) and wire frames (``encode_request``/
  ``encode_response``/``write_frame`` and the ``SolveRequest``/
  ``SolveResponse`` constructors).

A tainted value reaching a sink is a violation. Declared time inputs
(``snapshot.now``) are parameters, never tainted — the rule flags the
*introduction* of wall clock into the data plane, not its modeled use.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from koordinator_tpu.analysis.graftcheck.engine import (
    ModuleFile,
    Violation,
    attr_chain,
)

#: dotted chains whose CALL yields a nondeterministic value
_SOURCE_CHAINS = frozenset({
    "time.time", "time.time_ns", "time.perf_counter",
    "time.perf_counter_ns", "time.monotonic", "time.monotonic_ns",
    "os.urandom", "uuid.uuid4", "uuid.uuid1",
    "secrets.token_bytes", "secrets.token_hex", "secrets.randbits",
    "datetime.now", "datetime.utcnow", "datetime.datetime.now",
    "datetime.datetime.utcnow",
})

#: ``random.X(...)`` module-level draws (the shared, unseeded RNG)
_RANDOM_FNS = frozenset({
    "random", "randint", "randrange", "choice", "choices", "shuffle",
    "sample", "uniform", "gauss", "random_sample", "normal",
    "getrandbits",
})

#: order-insensitive folds that launder set-iteration taint, and
#: scalar launders for RNG/time taint where order is the only hazard
_LAUNDER_FNS = frozenset({
    "sorted", "len", "min", "max", "sum", "any", "all", "frozenset",
})

#: sequence constructors that MATERIALIZE iteration order
_ORDER_MATERIALIZERS = frozenset({"list", "tuple"})

#: wire-frame sinks (service/codec.py surface)
_WIRE_SINKS = frozenset({
    "encode_request", "encode_response", "write_frame", "_pack",
})
_WIRE_CTORS = frozenset({"SolveRequest", "SolveResponse"})

#: device-staging producers (mirrors host_sync.DEFAULT_PRODUCERS plus
#: the explicit staging entry points)
_DEVICE_SINKS = frozenset({
    "device_put",  # jnp.asarray/jnp.array ride the jnp-root check
    "stage_nodes", "stage_pods", "solve_batch", "schedule_batch",
    "pallas_solve_batch", "scatter_node_rows_donated",
    "scatter_node_rows_copied", "_dispatch_solve", "_solve",
})


def _last_seg(chain: str) -> str:
    return chain.split(".")[-1] if chain else ""


class DeterminismRule:
    name = "determinism-taint"
    description = (
        "wall clock, unseeded RNGs, and set iteration order never flow "
        "into device values or wire frames (bit-parity inputs)"
    )

    def __init__(self, scope: Sequence[str]):
        self.scope = tuple(scope)

    # -- taint classification ------------------------------------------------

    def _call_taint(self, call: ast.Call, tainted: Set[str],
                    sets: Set[str]) -> Optional[str]:
        """Taint label a call's RESULT carries, else None."""
        chain = attr_chain(call.func) or ""
        seg = _last_seg(chain)
        if chain in _SOURCE_CHAINS:
            return chain
        root = chain.split(".")[0] if chain else ""
        if root in ("random", "np.random", "numpy.random") or (
            root == "np" and chain.startswith("np.random.")
        ):
            if seg in _RANDOM_FNS:
                return chain
            if seg == "default_rng" and not call.args:
                return chain + "()"
        if chain == "random.Random" and not call.args:
            return "random.Random()"
        if seg in _LAUNDER_FNS:
            return None
        if seg in _ORDER_MATERIALIZERS and call.args:
            if self._is_set_valued(call.args[0], sets):
                return f"{seg}(<set>)"
        # propagate through arbitrary calls on tainted receivers/args
        # (str(t), t.hex(), jnp.float32(t)...) — a transform of a
        # nondeterministic value stays nondeterministic
        for sub in list(call.args) + [kw.value for kw in call.keywords]:
            if self._tainted(sub, tainted, sets):
                return self._expr_taint_label(sub, tainted, sets)
        if isinstance(call.func, ast.Attribute) and self._tainted(
            call.func.value, tainted, sets
        ):
            return self._expr_taint_label(call.func.value, tainted, sets)
        return None

    def _is_set_valued(self, node: ast.AST, sets: Set[str]) -> bool:
        if isinstance(node, ast.Set) or isinstance(node, ast.SetComp):
            return True
        if isinstance(node, ast.Call):
            chain = attr_chain(node.func) or ""
            if chain == "set" or _last_seg(chain) == "frozenset":
                return True
            # set operations keep set-ness (s.union(t), s & t)
            if isinstance(node.func, ast.Attribute) and \
                    self._is_set_valued(node.func.value, sets):
                return True
        if isinstance(node, ast.Name):
            return node.id in sets
        if isinstance(node, ast.BinOp) and isinstance(
            node.op, (ast.BitAnd, ast.BitOr, ast.BitXor, ast.Sub)
        ):
            return self._is_set_valued(node.left, sets) or \
                self._is_set_valued(node.right, sets)
        return False

    def _expr_taint_label(self, node: ast.AST, tainted: Set[str],
                          sets: Set[str]) -> str:
        if isinstance(node, ast.Name) and node.id in tainted:
            return node.id
        chain = attr_chain(node)
        if chain is not None and chain in tainted:
            return chain
        if isinstance(node, ast.Call):
            label = self._call_taint(node, tainted, sets)
            if label is not None:
                return label
        return "<nondet>"

    def _tainted(self, node: ast.AST, tainted: Set[str],
                 sets: Set[str]) -> bool:
        if isinstance(node, ast.Name):
            return node.id in tainted
        if isinstance(node, ast.Attribute):
            chain = attr_chain(node)
            if chain is not None and chain in tainted:
                return True
            return self._tainted(node.value, tainted, sets)
        if isinstance(node, ast.Call):
            return self._call_taint(node, tainted, sets) is not None
        if isinstance(node, ast.Subscript):
            return self._tainted(node.value, tainted, sets)
        if isinstance(node, ast.BinOp):
            return self._tainted(node.left, tainted, sets) or \
                self._tainted(node.right, tainted, sets)
        if isinstance(node, ast.UnaryOp):
            return self._tainted(node.operand, tainted, sets)
        if isinstance(node, ast.IfExp):
            return self._tainted(node.body, tainted, sets) or \
                self._tainted(node.orelse, tainted, sets)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any(self._tainted(e, tainted, sets)
                       for e in node.elts)
        if isinstance(node, ast.Dict):
            return any(
                self._tainted(v, tainted, sets)
                for v in list(node.keys) + list(node.values)
                if v is not None
            )
        if isinstance(node, ast.Starred):
            return self._tainted(node.value, tainted, sets)
        if isinstance(node, (ast.ListComp, ast.GeneratorExp)):
            # comprehension over a set-typed iterable materializes its
            # order; a tainted element expression taints too
            for gen in node.generators:
                if self._is_set_valued(gen.iter, sets):
                    return True
            return self._tainted(node.elt, tainted, sets)
        if isinstance(node, ast.NamedExpr):
            return self._tainted(node.value, tainted, sets)
        return False

    # -- sinks ---------------------------------------------------------------

    def _sink_kind(self, call: ast.Call,
                   producers: Set[str]) -> Optional[str]:
        chain = attr_chain(call.func) or ""
        seg = _last_seg(chain)
        root = chain.split(".")[0] if chain else ""
        if seg in _WIRE_SINKS or seg in _WIRE_CTORS:
            return "wire frame"
        if root == "jnp" or chain == "jax.device_put":
            return "device value"
        if seg in _DEVICE_SINKS or seg in producers:
            return "device value"
        return None

    # -- statement walk ------------------------------------------------------

    def check(self, module: ModuleFile) -> List[Violation]:
        if not module.matches(self.scope):
            return []
        out: List[Violation] = []
        producers: Set[str] = set()
        # discover jitted bindings: X = jax.jit(...) makes X a device
        # sink for this module
        for node in ast.walk(module.tree):
            if isinstance(node, ast.Assign) and isinstance(
                node.value, ast.Call
            ):
                chain = attr_chain(node.value.func) or ""
                if _last_seg(chain) in ("jit", "pjit"):
                    for t in node.targets:
                        seg = (
                            t.attr if isinstance(t, ast.Attribute)
                            else t.id if isinstance(t, ast.Name)
                            else None
                        )
                        if seg is not None:
                            producers.add(seg)
        self._scan(module.tree.body, set(), set(), producers, [],
                   module.path, out)
        return out

    def _scan(self, stmts, tainted: Set[str], sets: Set[str],
              producers: Set[str], scopes: List[str], path: str,
              out: List[Violation]) -> None:
        qualname = ".".join(scopes) if scopes else "<module>"

        def check_expr(expr: Optional[ast.AST]) -> None:
            if expr is None:
                return
            for sub in ast.walk(expr):
                if not isinstance(sub, ast.Call):
                    continue
                kind = self._sink_kind(sub, producers)
                if kind is None:
                    continue
                for a in list(sub.args) + [
                    kw.value for kw in sub.keywords
                ]:
                    if self._tainted(a, tainted, sets):
                        label = self._expr_taint_label(a, tainted, sets)
                        chain = attr_chain(sub.func) or "?"
                        out.append(Violation(
                            rule=self.name, path=path,
                            line=sub.lineno, col=sub.col_offset,
                            func=qualname, symbol=label,
                            message=(
                                f"nondeterministic value ({label}) "
                                f"flows into {kind} via {chain}(...) — "
                                f"bit-parity poisoned"
                            ),
                        ))
                        break

        def assign(target: ast.AST, is_tainted: bool,
                   is_set: bool) -> None:
            if isinstance(target, ast.Name):
                (tainted.add if is_tainted else
                 tainted.discard)(target.id)
                (sets.add if is_set else sets.discard)(target.id)
            elif isinstance(target, ast.Attribute):
                chain = attr_chain(target)
                if chain is not None:
                    (tainted.add if is_tainted else
                     tainted.discard)(chain)
            elif isinstance(target, (ast.Tuple, ast.List)):
                for e in target.elts:
                    assign(e, is_tainted, is_set)
            elif isinstance(target, ast.Starred):
                assign(target.value, is_tainted, is_set)

        for stmt in stmts:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef,
                                 ast.ClassDef)):
                self._scan(stmt.body, set(tainted), set(sets),
                           set(producers), scopes + [stmt.name], path,
                           out)
            elif isinstance(stmt, (ast.Assign, ast.AnnAssign)):
                value = stmt.value
                check_expr(value)
                if value is None:
                    continue
                is_t = self._tainted(value, tainted, sets)
                is_s = self._is_set_valued(value, sets)
                targets = stmt.targets if isinstance(
                    stmt, ast.Assign) else [stmt.target]
                for t in targets:
                    assign(t, is_t, is_s)
            elif isinstance(stmt, ast.AugAssign):
                check_expr(stmt.value)
                if self._tainted(stmt.value, tainted, sets):
                    assign(stmt.target, True, False)
            elif isinstance(stmt, (ast.Expr, ast.Return)):
                check_expr(stmt.value)
            elif isinstance(stmt, ast.If):
                check_expr(stmt.test)
                self._scan(stmt.body, tainted, sets, producers, scopes,
                           path, out)
                self._scan(stmt.orelse, tainted, sets, producers,
                           scopes, path, out)
            elif isinstance(stmt, (ast.For, ast.AsyncFor)):
                check_expr(stmt.iter)
                # iterating a set binds loop vars in hash order; the
                # VALUES are deterministic, the ORDER is not — the loop
                # var itself is only order-tainted when its iteration
                # order is materialized into a sequence, which the
                # comprehension/list()/tuple() cases cover. A plain
                # tainted iterable taints the loop var.
                assign(stmt.target,
                       self._tainted(stmt.iter, tainted, sets), False)
                self._scan(stmt.body, tainted, sets, producers, scopes,
                           path, out)
                self._scan(stmt.orelse, tainted, sets, producers,
                           scopes, path, out)
            elif isinstance(stmt, ast.While):
                check_expr(stmt.test)
                self._scan(stmt.body, tainted, sets, producers, scopes,
                           path, out)
                self._scan(stmt.orelse, tainted, sets, producers,
                           scopes, path, out)
            elif isinstance(stmt, (ast.With, ast.AsyncWith)):
                for item in stmt.items:
                    check_expr(item.context_expr)
                    if item.optional_vars is not None:
                        assign(
                            item.optional_vars,
                            self._tainted(item.context_expr, tainted,
                                          sets),
                            False,
                        )
                self._scan(stmt.body, tainted, sets, producers, scopes,
                           path, out)
            elif isinstance(stmt, ast.Try):
                for block in (stmt.body, stmt.orelse, stmt.finalbody):
                    self._scan(block, tainted, sets, producers, scopes,
                               path, out)
                for handler in stmt.handlers:
                    self._scan(handler.body, tainted, sets, producers,
                               scopes, path, out)
            elif isinstance(stmt, ast.Match):
                check_expr(stmt.subject)
                for case in stmt.cases:
                    check_expr(case.guard)
                    self._scan(case.body, tainted, sets, producers,
                               scopes, path, out)
            elif isinstance(stmt, (ast.Raise, ast.Assert, ast.Delete)):
                for child in ast.iter_child_nodes(stmt):
                    check_expr(child)
