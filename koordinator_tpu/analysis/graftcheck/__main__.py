"""CLI: ``python -m koordinator_tpu.analysis.graftcheck``.

Runs every rule repo-wide against the allowlist at
``<repo-root>/graftcheck.toml`` and exits non-zero on any unsuppressed
violation. ``--rule`` narrows to named rules (repeatable);
``--format=json`` emits machine-readable output (bench.py folds the
violation count into every bench record).
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

from koordinator_tpu.analysis.graftcheck.engine import (
    iter_repo_modules,
    load_allowlist,
    render,
    run_checks,
)
from koordinator_tpu.analysis.graftcheck.rules import default_rules


def find_repo_root(start: Path) -> Path:
    """The directory holding the ``koordinator_tpu`` package (and the
    allowlist) — walked up from this file so the CLI works from any
    cwd."""
    for candidate in (start, *start.parents):
        if (candidate / "koordinator_tpu" / "__init__.py").exists():
            return candidate
    raise SystemExit("graftcheck: cannot locate repo root")


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="graftcheck")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument(
        "--rule", action="append", default=None,
        help="run only the named rule(s); repeatable",
    )
    parser.add_argument(
        "--root", default=None,
        help="repo root (default: auto-detected from the package path)",
    )
    args = parser.parse_args(argv)

    root = (
        Path(args.root).resolve() if args.root
        else find_repo_root(Path(__file__).resolve())
    )
    rules = default_rules()
    if args.rule:
        known = {r.name for r in rules}
        unknown = set(args.rule) - known
        if unknown:
            parser.error(
                f"unknown rule(s) {sorted(unknown)}; known: {sorted(known)}"
            )
        rules = tuple(r for r in rules if r.name in args.rule)
    allowlist = load_allowlist(root / "graftcheck.toml")
    if args.rule:
        # a narrowed run must not report entries for skipped rules as
        # stale — they simply were not exercised
        names = set(args.rule)
        allowlist = [e for e in allowlist if e.rule in names]
    violations, suppressed = run_checks(
        iter_repo_modules(root), rules, allowlist
    )
    print(render(violations, suppressed, args.format))
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
