"""CLI: ``python -m koordinator_tpu.analysis.graftcheck``.

Runs every rule repo-wide against the allowlist at
``<repo-root>/graftcheck.toml`` and exits non-zero on any unsuppressed
violation. ``--rule`` narrows to named rules (repeatable);
``--format=json`` emits machine-readable output including per-rule
wall time and violation counts (bench.py folds both into every bench
record).

``--changed-files`` is the incremental mode that keeps the check.sh
gate fast as the repo grows: local rules scan only the named files
(comma-separated repo-relative paths, or ``auto`` to take the set from
``git diff --name-only HEAD`` plus untracked files), while the
whole-program passes — sync-reach, lock-order, donation-safety — still
load the FULL call graph: their properties span files a diff never
names. ``auto`` with a clean tree falls back to the full scan, so a
post-commit CI run never silently checks nothing.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path

from koordinator_tpu.analysis.graftcheck.engine import (
    iter_repo_modules,
    load_allowlist,
    render,
    run_checks_timed,
)
from koordinator_tpu.analysis.graftcheck.rules import default_rules


def find_repo_root(start: Path) -> Path:
    """The directory holding the ``koordinator_tpu`` package (and the
    allowlist) — walked up from this file so the CLI works from any
    cwd."""
    for candidate in (start, *start.parents):
        if (candidate / "koordinator_tpu" / "__init__.py").exists():
            return candidate
    raise SystemExit("graftcheck: cannot locate repo root")


def git_changed_files(root: Path) -> list:
    """Repo-relative paths touched since HEAD (diffed + untracked)."""
    out = []
    for args in (
        ["git", "diff", "--name-only", "HEAD"],
        ["git", "ls-files", "--others", "--exclude-standard"],
    ):
        try:
            proc = subprocess.run(
                args, cwd=root, capture_output=True, text=True,
                timeout=30,
            )
        except (OSError, subprocess.TimeoutExpired):
            return []
        if proc.returncode != 0:
            return []
        out.extend(
            line.strip() for line in proc.stdout.splitlines()
            if line.strip()
        )
    return out


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="graftcheck")
    parser.add_argument("--format", choices=("text", "json"),
                        default="text")
    parser.add_argument(
        "--rule", action="append", default=None,
        help="run only the named rule(s); repeatable",
    )
    parser.add_argument(
        "--root", default=None,
        help="repo root (default: auto-detected from the package path)",
    )
    parser.add_argument(
        "--changed-files", default=None, metavar="PATHS|auto",
        help="incremental mode: local rules scan only these comma-"
             "separated repo-relative files ('auto' = git diff + "
             "untracked; empty auto set falls back to a full scan); "
             "whole-program rules always analyze the full call graph",
    )
    args = parser.parse_args(argv)

    root = (
        Path(args.root).resolve() if args.root
        else find_repo_root(Path(__file__).resolve())
    )
    rules = default_rules()
    if args.rule:
        known = {r.name for r in rules}
        unknown = set(args.rule) - known
        if unknown:
            parser.error(
                f"unknown rule(s) {sorted(unknown)}; known: {sorted(known)}"
            )
        rules = tuple(r for r in rules if r.name in args.rule)
    allowlist = load_allowlist(root / "graftcheck.toml")
    if args.rule:
        # a narrowed run must not report entries for skipped rules as
        # stale — they simply were not exercised
        names = set(args.rule)
        allowlist = [e for e in allowlist if e.rule in names]

    changed = None
    if args.changed_files is not None:
        if args.changed_files.strip() == "auto":
            changed = git_changed_files(root)
            if not changed:
                changed = None  # clean tree: full scan, never a no-op
        else:
            changed = [
                p.strip() for p in args.changed_files.split(",")
                if p.strip()
            ]

    violations, suppressed, stats = run_checks_timed(
        iter_repo_modules(root), rules, allowlist, changed=changed,
    )
    if args.format == "json":
        payload = json.loads(render(violations, suppressed, "json"))
        payload["rules"] = {
            name: {
                "wall_s": round(s["wall_s"], 4),
                "violations": s["violations"],
            }
            for name, s in sorted(stats.items())
        }
        payload["changed_files"] = sorted(changed) if changed else None
        # the signature-space sidecar (ISSUE 15): per-binding enumerated
        # axis images + the signature-space bound, machine-readable —
        # what the warm manifest must cover and the runtime sentinel
        # asserts against (docs/DESIGN.md §23)
        for rule in rules:
            if getattr(rule, "name", "") == "signature-space":
                payload["signature_space"] = rule.last_space
        print(json.dumps(payload, indent=2))
    else:
        print(render(violations, suppressed, "text"))
        if changed:
            print(
                f"graftcheck: incremental over {len(changed)} changed "
                f"file(s); whole-program rules ran on the full graph"
            )
    return 1 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
