"""In-repo static analysis for the jax_graft invariants.

The reference Koordinator leans on Go's race detector and ``go vet`` to
keep its informer/cache concurrency honest; the TPU port's equivalents
live here. ``graftcheck`` is the AST invariant checker for the solve hot
path (see ``koordinator_tpu/analysis/graftcheck/``).
"""
