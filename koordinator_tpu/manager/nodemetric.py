"""nodemetric controller: reconciles the metric collect policy per node.

Reference: pkg/slo-controller/nodemetric/{nodemetric_controller.go,
collect_policy.go} — the manager creates a NodeMetric CR per node and
stamps the collect policy (aggregate duration / report interval) derived
from the colocation strategy; koordlet reads it to pace its reporting.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, Optional

from koordinator_tpu.manager.sloconfig import ColocationConfig, ColocationStrategy


@dataclasses.dataclass
class NodeMetricCollectPolicy:
    """Reference: slov1alpha1.NodeMetricCollectPolicy."""

    aggregate_duration_seconds: int
    report_interval_seconds: int
    #: aggregation durations for percentile stats (p50/p90/p95/p99)
    aggregate_durations: tuple = (300, 900, 1800)


def node_metric_collect_policy(
    strategy: ColocationStrategy,
) -> Optional[NodeMetricCollectPolicy]:
    """Reference: getNodeMetricCollectPolicy (collect_policy.go:28-48):
    None when the strategy is invalid or colocation disabled."""
    if not strategy.is_valid() or not strategy.enable:
        return None
    return NodeMetricCollectPolicy(
        aggregate_duration_seconds=strategy.metric_aggregate_duration_seconds,
        report_interval_seconds=strategy.metric_report_interval_seconds,
    )


def reconcile_collect_policies(
    config: ColocationConfig, node_labels: Dict[str, Dict[str, str]]
) -> Dict[str, Optional[NodeMetricCollectPolicy]]:
    """Per-node policies, honoring node-selector strategy overrides."""
    return {
        name: node_metric_collect_policy(config.strategy_for_node(labels))
        for name, labels in node_labels.items()
    }
