"""Cluster SLO configuration: colocation strategy + NodeSLO strategies.

Reference: apis/configuration/slo_controller_config.go (schema) and
pkg/util/sloconfig/{colocation_config.go,nodeslo_config.go} (defaults).
The reference stores these in `koordinator-system` ConfigMaps; here they
are plain dataclasses parsed from dicts (the ConfigMap JSON payloads),
with the same default values and the same per-node override merge
(cluster strategy -> node-selector strategies -> node annotation).
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.apis.types import selector_matches


def merge_overrides(base, overrides: Dict):
    """Recursive JSON-merge-patch overlay: only keys present in
    ``overrides`` change; nested dicts recurse into nested dataclasses
    (reference: the sloconfig ConfigMap node-strategy merge, which
    strategic-merges only the fields the override JSON sets). Returns a
    new dataclass; ``base`` is not mutated."""
    import copy

    out = copy.deepcopy(base)
    for key, value in overrides.items():
        if not hasattr(out, key):
            continue
        current = getattr(out, key)
        if isinstance(value, dict) and dataclasses.is_dataclass(current):
            setattr(out, key, merge_overrides(current, value))
        else:
            setattr(out, key, value)
    return out


# ---------------------------------------------------------------------------
# Colocation strategy (drives noderesource + nodemetric)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ColocationStrategy:
    """Reference: configuration.ColocationStrategy with defaults from
    pkg/util/sloconfig/colocation_config.go:50-75."""

    enable: bool = False
    metric_aggregate_duration_seconds: int = 300
    metric_report_interval_seconds: int = 60
    cpu_reclaim_threshold_percent: int = 60
    memory_reclaim_threshold_percent: int = 65
    degrade_time_minutes: int = 15
    update_time_threshold_seconds: int = 300
    resource_diff_threshold: float = 0.1
    mid_cpu_threshold_percent: int = 100
    mid_memory_threshold_percent: int = 100
    # CalculatePolicy names: "usage" | "request" | "maxUsageRequest"
    cpu_calculate_policy: str = "usage"
    memory_calculate_policy: str = "usage"

    def is_valid(self) -> bool:
        """Reference: sloconfig.IsColocationStrategyValid
        (colocation_config.go:77-85)."""
        return (
            self.metric_aggregate_duration_seconds > 0
            and self.metric_report_interval_seconds > 0
            and 0 < self.cpu_reclaim_threshold_percent <= 100
            and 0 < self.memory_reclaim_threshold_percent <= 100
            and self.degrade_time_minutes > 0
            and self.update_time_threshold_seconds > 0
            and self.resource_diff_threshold > 0
        )

    @classmethod
    def from_dict(cls, d: Dict) -> "ColocationStrategy":
        known = {f.name for f in dataclasses.fields(cls)}
        return cls(**{k: v for k, v in d.items() if k in known})


@dataclasses.dataclass
class NodeStrategySelector:
    """A node-scoped strategy override selected by labels (reference:
    configuration.NodeColocationCfg / NodeStrategy). ``overrides`` holds
    only the fields the override sets (JSON-merge-patch semantics)."""

    match_labels: Dict[str, str]
    overrides: Dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ColocationConfig:
    """Cluster config + node overrides (reference:
    configuration.ColocationCfg)."""

    cluster_strategy: ColocationStrategy = dataclasses.field(
        default_factory=ColocationStrategy
    )
    node_strategies: List[NodeStrategySelector] = dataclasses.field(
        default_factory=list
    )

    def strategy_for_node(self, node_labels: Dict[str, str]) -> ColocationStrategy:
        """Cluster strategy overlaid with the first matching node strategy
        (reference: config_cache.go GetStrategyCopy + merge)."""
        out = self.cluster_strategy
        for sel in self.node_strategies:
            if selector_matches(sel.match_labels, node_labels):
                out = merge_overrides(out, sel.overrides)
                break
        return out


# ---------------------------------------------------------------------------
# NodeSLO strategies (rendered into per-node NodeSLO by the nodeslo
# controller; consumed by koordlet's qosmanager)
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class ResourceThresholdStrategy:
    """Reference: slov1alpha1.ResourceThresholdStrategy, defaults
    nodeslo_config.go:53-61."""

    enable: bool = False
    cpu_suppress_threshold_percent: int = 65
    cpu_suppress_policy: str = "cpuset"  # cpuset | cfsQuota
    memory_evict_threshold_percent: int = 70
    memory_evict_lower_percent: Optional[int] = None  # default threshold-2
    cpu_evict_policy: str = "evictByRealLimit"
    cpu_evict_be_usage_threshold_percent: int = 90
    cpu_evict_be_satisfaction_lower_percent: Optional[int] = None
    cpu_evict_be_satisfaction_upper_percent: Optional[int] = None
    cpu_evict_time_window_seconds: int = 60


@dataclasses.dataclass
class CPUQOS:
    """Per-QoS cpu knobs (reference: slov1alpha1.CPUQOS, defaults
    nodeslo_config.go:64-97): bvt group identity, SCHED_IDLE, core
    expeller."""

    group_identity: int = 0
    sched_idle: int = 0
    core_expeller: bool = False


@dataclasses.dataclass
class MemoryQOS:
    """Reference: slov1alpha1.MemoryQOS (memcg qos), defaults all-off
    (nodeslo_config.go:136-190)."""

    min_limit_percent: int = 0
    low_limit_percent: int = 0
    throttling_percent: int = 0
    wmark_ratio: int = 95
    wmark_scale_permill: int = 20
    wmark_min_adj: int = 0
    oom_kill_group: int = 0
    priority_enable: int = 0
    priority: int = 0


@dataclasses.dataclass
class ResctrlQOS:
    """Reference: slov1alpha1.ResctrlQOS, defaults nodeslo_config.go:
    100-130: BE gets 0-30% of LLC ways, others full; MBA 100%."""

    cat_range_start_percent: int = 0
    cat_range_end_percent: int = 100
    mba_percent: int = 100


@dataclasses.dataclass
class BlockCfg:
    """One throttled block device (reference: slov1alpha1.BlockCfg/
    BlkIOQOS, blkio_reconcile.go:311-373 getBlkIOUpdaterFromBlockCfg).
    Devices are addressed by their MAJ:MIN number; 0 = unlimited.

    ``block_type="pod_volume"`` addresses a pod volume by name instead:
    the reconciler resolves volume -> PVC claim -> bound PV (the PVC
    informer's map) -> device (blkio_reconcile.go:375-418
    getDiskNumberFromBlockCfg, BlockTypePodVolume)."""

    device: str = ""            # "MAJ:MIN" (block_type="device")
    read_bps: int = 0
    write_bps: int = 0
    read_iops: int = 0
    write_iops: int = 0
    block_type: str = "device"  # "device" | "pod_volume"
    name: str = ""              # volume name (block_type="pod_volume")


@dataclasses.dataclass
class NetworkQOS:
    """Per-class network bandwidth QoS (reference: slov1alpha1
    NetworkQOSCfg). Request/limit values follow the reference's
    IntOrString convention: an int is a percentage of the node's total
    bandwidth; a str is an absolute bits-per-second quantity
    (terwayqos.go:352-371 parseQuantity)."""

    enable: bool = False
    ingress_request: Optional[object] = None  # int % | str bits/s
    ingress_limit: Optional[object] = None
    egress_request: Optional[object] = None
    egress_limit: Optional[object] = None


@dataclasses.dataclass
class QoSConfig:
    enable: bool = False
    cpu: CPUQOS = dataclasses.field(default_factory=CPUQOS)
    memory: MemoryQOS = dataclasses.field(default_factory=MemoryQOS)
    resctrl: ResctrlQOS = dataclasses.field(default_factory=ResctrlQOS)
    blkio: List[BlockCfg] = dataclasses.field(default_factory=list)
    network: NetworkQOS = dataclasses.field(default_factory=NetworkQOS)


def default_qos_config(qos: QoSClass) -> QoSConfig:
    """Per-class defaults (reference: DefaultResourceQOSStrategy,
    nodeslo_config.go:64-130): LSR/LS bvt=2 + core expeller, BE bvt=-1 and
    LLC capped to 30%."""
    cfg = QoSConfig()
    if qos in (QoSClass.LSR, QoSClass.LS):
        cfg.cpu = CPUQOS(group_identity=2, core_expeller=True)
    elif qos is QoSClass.BE:
        cfg.cpu = CPUQOS(group_identity=-1)
        cfg.resctrl = ResctrlQOS(cat_range_end_percent=30)
    return cfg


@dataclasses.dataclass
class ResourceQOSStrategy:
    lsr: QoSConfig = dataclasses.field(
        default_factory=lambda: default_qos_config(QoSClass.LSR)
    )
    ls: QoSConfig = dataclasses.field(
        default_factory=lambda: default_qos_config(QoSClass.LS)
    )
    be: QoSConfig = dataclasses.field(
        default_factory=lambda: default_qos_config(QoSClass.BE)
    )
    system: QoSConfig = dataclasses.field(
        default_factory=lambda: default_qos_config(QoSClass.SYSTEM)
    )

    #: strategy-level policy switches (reference: ResourceQOSPolicies);
    #: key "netQOSPolicy" == "terway-qos" enables the terway net-QoS hook
    policies: Dict[str, str] = dataclasses.field(default_factory=dict)

    def for_qos(self, qos: QoSClass) -> QoSConfig:
        return {
            QoSClass.LSE: self.lsr,  # LSE shares LSR's knobs
            QoSClass.LSR: self.lsr,
            QoSClass.LS: self.ls,
            QoSClass.BE: self.be,
            QoSClass.SYSTEM: self.system,
        }.get(qos, self.ls)


@dataclasses.dataclass
class CPUBurstStrategy:
    """Reference: slov1alpha1.CPUBurstStrategy, defaults
    nodeslo_config.go:360-374."""

    policy: str = "none"  # none | cpuBurstOnly | cfsQuotaBurstOnly | auto
    cpu_burst_percent: int = 1000
    cfs_quota_burst_percent: int = 300
    cfs_quota_burst_period_seconds: int = -1  # -1: always allowed
    share_pool_threshold_percent: int = 50


@dataclasses.dataclass
class SystemStrategy:
    """Reference: slov1alpha1.SystemStrategy, defaults
    nodeslo_config.go:376-382."""

    min_free_kbytes_factor: int = 100   # 1/10000 of total memory
    watermark_scale_factor: int = 150   # 1/10000
    memcg_reap_background: int = 0
    #: node NIC capacity in bits/s (reference: SystemStrategy
    #: TotalNetworkBandwidth); 0 = unknown (net QoS disabled)
    total_network_bandwidth_bps: int = 0


@dataclasses.dataclass
class HostApplicationSpec:
    """A non-pod host process under QoS management (reference:
    slov1alpha1 host_application.go HostApplicationSpec): named, with a
    QoS class and the cgroup directory its processes live in."""

    name: str
    qos: QoSClass = QoSClass.NONE
    cgroup_dir: str = ""
    priority: int = 0


@dataclasses.dataclass
class NodeSLOSpec:
    """The rendered per-node SLO (reference: slov1alpha1.NodeSLOSpec)."""

    resource_used_threshold_with_be: ResourceThresholdStrategy = (
        dataclasses.field(default_factory=ResourceThresholdStrategy)
    )
    resource_qos_strategy: ResourceQOSStrategy = dataclasses.field(
        default_factory=ResourceQOSStrategy
    )
    cpu_burst_strategy: CPUBurstStrategy = dataclasses.field(
        default_factory=CPUBurstStrategy
    )
    system_strategy: SystemStrategy = dataclasses.field(
        default_factory=SystemStrategy
    )
    host_applications: List[HostApplicationSpec] = dataclasses.field(
        default_factory=list
    )
    extensions: Dict[str, object] = dataclasses.field(default_factory=dict)


def default_node_slo_spec() -> NodeSLOSpec:
    """Reference: sloconfig.DefaultNodeSLOSpecConfig
    (nodeslo_config.go:43-51)."""
    return NodeSLOSpec()
