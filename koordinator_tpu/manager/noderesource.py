"""noderesource controller: the colocation overcommit reconciler.

Reference: pkg/slo-controller/noderesource/ (noderesource_controller.go,
resource_calculator.go, plugins_profile.go) — watches NodeMetric + Node +
pods and writes dynamically-reclaimable batch/mid extended resources into
``Node.status.allocatable``.

TPU-native design: the reference reconciles node-by-node through a plugin
pipeline (Setup/PreUpdate/NeedSync/Prepare/Calculate). Here ONE
``reconcile_all`` lowers the whole cluster to arrays and computes every
node's batch+mid allocatable in a single jitted XLA program
(ops/overcommit.py); host-side plugins then run only the annotation-type
preparations (cpu-normalization -> amplification) that are inherently
string-typed. NeedSync's diff-threshold gate is part of the same fused
program.
"""

from __future__ import annotations

import dataclasses
import json
from functools import partial
from typing import Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.apis.extension import (
    ANNOTATION_CPU_NORMALIZATION_RATIO,
    ANNOTATION_NODE_RAW_ALLOCATABLE,
    parse_node_reservation,
    ANNOTATION_RESOURCE_AMPLIFICATION_RATIO,
    NUM_RESOURCES,
    PriorityClass,
    QoSClass,
    ResourceName,
)
from koordinator_tpu.apis.types import (
    ClusterSnapshot,
    NodeMetric,
    NodeSpec,
    resources_to_vector,
)
from koordinator_tpu.manager.sloconfig import ColocationConfig, ColocationStrategy
from koordinator_tpu.ops.overcommit import (
    CalculatePolicy,
    NodeOvercommitInputs,
    OvercommitParams,
    PodOvercommitInputs,
    needs_sync,
    overcommit_allocatable,
)

_POLICY_BY_NAME = {
    "usage": CalculatePolicy.USAGE,
    "request": CalculatePolicy.REQUEST,
    "maxUsageRequest": CalculatePolicy.MAX_USAGE_REQUEST,
}

#: Extended resource columns owned by this controller.
OVERCOMMIT_COLUMNS = (
    ResourceName.BATCH_CPU,
    ResourceName.BATCH_MEMORY,
    ResourceName.MID_CPU,
    ResourceName.MID_MEMORY,
)


@dataclasses.dataclass
class NodeResourceUpdate:
    """One node's reconcile outcome."""

    node_name: str
    #: new values for the overcommit columns (canonical units)
    allocatable: Dict[ResourceName, int]
    #: whether the diff threshold requires writing back
    synced: bool
    #: degraded to zero because the NodeMetric was stale/missing
    degraded: bool
    #: annotations to set on the node (amplification etc.)
    annotations: Dict[str, str] = dataclasses.field(default_factory=dict)
    #: node metadata (annotations / native allocatable) changed and must
    #: be written back even when the overcommit diff is below threshold
    #: (reference: the plugins' NeedSyncMeta surface)
    meta_synced: bool = False


def _is_metric_fresh(
    metric: Optional[NodeMetric], strategy: ColocationStrategy, now: float
) -> bool:
    """Degrade gate (reference: batchresource/plugin.go:480-499
    isDegradeNeeded): no metric or update older than DegradeTimeMinutes."""
    if metric is None or metric.update_time <= 0:
        return False
    return now - metric.update_time <= strategy.degrade_time_minutes * 60


class HostPlugin:
    """Annotation-type noderesource plugin (host-side).

    Mirrors the reference's plugin Prepare/NeedSyncMeta surface for
    plugins whose output is node metadata rather than array math
    (reference: plugins/{cpunormalization,resourceamplification}/).
    """

    name = "hostplugin"

    def prepare(self, node: NodeSpec, update: NodeResourceUpdate) -> None:
        raise NotImplementedError


#: Amplification ratios beyond this are treated as malformed: real cpu
#: normalization ratios are ~1-2x, and huge values would overflow the
#: int32 capacity columns.
_MAX_NORMALIZATION_RATIO = 100.0


def _cpu_normalization_ratio(node: NodeSpec) -> Optional[float]:
    """Parsed cpu-normalization ratio, or None when absent/malformed/not
    amplifying (reference: extension.GetCPUNormalizationRatio: ratio <= 1
    means no amplification)."""
    raw = node.annotations.get(ANNOTATION_CPU_NORMALIZATION_RATIO)
    if raw is None:
        return None
    try:
        ratio = float(raw)
    except ValueError:
        return None
    # rejects NaN, inf, and int32-overflowing values
    if not 1 < ratio <= _MAX_NORMALIZATION_RATIO:
        return None
    return ratio


class ResourceAmplificationPlugin(HostPlugin):
    """Sets the node resource-amplification ratio annotation from the cpu
    normalization ratio (reference:
    plugins/resourceamplification/plugin.go:82-115 Calculate: ratio <= 1
    -> no annotation; else {"cpu": ratio})."""

    name = "ResourceAmplification"

    def prepare(self, node: NodeSpec, update: NodeResourceUpdate) -> None:
        ratio = _cpu_normalization_ratio(node)
        if ratio is None:
            return
        value = json.dumps({"cpu": ratio})
        update.annotations[ANNOTATION_RESOURCE_AMPLIFICATION_RATIO] = value
        if node.annotations.get(ANNOTATION_RESOURCE_AMPLIFICATION_RATIO) != value:
            node.annotations[ANNOTATION_RESOURCE_AMPLIFICATION_RATIO] = value
            update.meta_synced = True


class CPUNormalizationPlugin(HostPlugin):
    """Amplifies node CPU allocatable by the normalization ratio, keeping
    the raw value in an annotation (reference:
    plugins/cpunormalization/plugin.go Prepare + extension
    GetCPUNormalizationRatio). Amplification applies to the native CPU
    column the scheduler sees; when the ratio is removed or drops to <= 1
    the raw allocatable is restored."""

    name = "CPUNormalization"

    def prepare(self, node: NodeSpec, update: NodeResourceUpdate) -> None:
        ratio = _cpu_normalization_ratio(node)
        old_cpu = node.allocatable.get(ResourceName.CPU, 0)
        if ratio is None:
            if node.raw_allocatable is not None:
                node.allocatable[ResourceName.CPU] = node.raw_allocatable.get(
                    ResourceName.CPU, old_cpu
                )
                node.raw_allocatable = None
                update.meta_synced = True
            return
        base_cpu = old_cpu
        if node.raw_allocatable is None:
            node.raw_allocatable = dict(node.allocatable)
        else:
            base_cpu = node.raw_allocatable.get(ResourceName.CPU, base_cpu)
        amplified = int(base_cpu * ratio)
        if amplified != old_cpu:
            update.meta_synced = True
        node.allocatable[ResourceName.CPU] = amplified
        update.annotations[ANNOTATION_NODE_RAW_ALLOCATABLE] = json.dumps(
            {"cpu": base_cpu}
        )


@partial(jax.jit, static_argnames=())
def _overcommit_step(nodes, pods, params, old_alloc, diff_threshold_percent,
                     enabled):
    new_alloc = overcommit_allocatable(nodes, pods, params)
    # strategy disabled -> batch/mid resources are withdrawn (the
    # reference resets the extended resources when colocation turns off),
    # and needs_sync then fires iff the old values were nonzero
    new_alloc = jnp.where(enabled[:, None], new_alloc, 0)
    sync = needs_sync(old_alloc, new_alloc, diff_threshold_percent)
    return new_alloc, sync


class NodeResourceController:
    """Batched equivalent of the noderesource reconciler."""

    def __init__(self, config: Optional[ColocationConfig] = None,
                 plugins: Optional[Sequence[HostPlugin]] = None):
        self.config = config or ColocationConfig(
            cluster_strategy=ColocationStrategy(enable=True)
        )
        self.plugins: List[HostPlugin] = list(
            plugins
            if plugins is not None
            else (CPUNormalizationPlugin(), ResourceAmplificationPlugin())
        )
        #: node name -> time of the last synced write-back, for the
        #: periodic force-update gate (update_time_threshold_seconds)
        self._last_sync: Dict[str, float] = {}

    # -- lowering -----------------------------------------------------------

    def _lower_nodes(
        self, snapshot: ClusterSnapshot, strategies: List[ColocationStrategy]
    ) -> NodeOvercommitInputs:
        n = len(snapshot.nodes)
        capacity = np.zeros((n, NUM_RESOURCES), np.int32)
        system_used = np.zeros((n, NUM_RESOURCES), np.int32)
        reserved = np.zeros((n, NUM_RESOURCES), np.int32)
        prod_reclaimable = np.zeros((n, NUM_RESOURCES), np.int32)
        fresh = np.zeros(n, bool)
        for i, node in enumerate(snapshot.nodes):
            capacity[i] = resources_to_vector(node.allocatable)
            metric = snapshot.node_metrics.get(node.name)
            fresh[i] = _is_metric_fresh(metric, strategies[i], snapshot.now)
            if metric is not None:
                system_used[i] = resources_to_vector(metric.sys_usage)
                # BE host applications run on reclaimed resources: their
                # usage must not shrink batch capacity (reference:
                # batchresource plugin — hostAppBEUsed subtracted from
                # systemUsed, clamped at zero)
                for app, usage in metric.host_app_usages.items():
                    if metric.host_app_qos.get(app) == QoSClass.BE:
                        system_used[i] = np.maximum(
                            system_used[i] - resources_to_vector(usage), 0
                        )
                prod_reclaimable[i] = resources_to_vector(
                    metric.prod_reclaimable
                )
            # shared parse (apis/extension.parse_node_reservation):
            # malformed annotations on one node must not abort the
            # cluster-wide reconcile; the batch calculator subtracts the
            # reservation regardless of applyPolicy
            # (GetNodeReservationFromAnnotation, node.go:85-100)
            spec = parse_node_reservation(node.annotations)
            if spec is not None:
                reserved[i, ResourceName.CPU] = spec["cpu"]
                reserved[i, ResourceName.MEMORY] = spec["memory"]
        return NodeOvercommitInputs(
            capacity=jnp.asarray(capacity),
            system_used=jnp.asarray(system_used),
            reserved=jnp.asarray(reserved),
            prod_reclaimable=jnp.asarray(prod_reclaimable),
            metric_fresh=jnp.asarray(fresh),
        )

    def _lower_pods(
        self, snapshot: ClusterSnapshot, node_index: Dict[str, int]
    ) -> PodOvercommitInputs:
        rows = []  # (node_idx, req, usage, has_metric, is_hp, is_lse)
        seen_uids = set()
        for pod in snapshot.pods:
            idx = node_index.get(pod.node_name or "", -1)
            metric = snapshot.node_metrics.get(pod.node_name or "")
            usage = None
            if metric is not None and pod.uid in metric.pod_usages:
                usage = resources_to_vector(metric.pod_usages[pod.uid])
                seen_uids.add(pod.uid)
            is_hp = pod.priority_class not in (
                PriorityClass.BATCH,
                PriorityClass.FREE,
            )
            rows.append((
                idx,
                resources_to_vector(pod.requests),
                usage if usage is not None else np.zeros(NUM_RESOURCES, np.int64),
                usage is not None,
                is_hp,
                pod.qos is QoSClass.LSE,
            ))
        # dangling: reported in NodeMetric but absent from the pod list
        # (reference: plugin.go:295-303). Modeled as req=0 rows; priority
        # from the metric's recorded class, defaulting to HP.
        for node_name, metric in snapshot.node_metrics.items():
            idx = node_index.get(node_name, -1)
            for uid, usage in metric.pod_usages.items():
                if uid in seen_uids:
                    continue
                cls = metric.pod_priority_class.get(uid, PriorityClass.PROD)
                if cls in (PriorityClass.BATCH, PriorityClass.FREE):
                    continue
                rows.append((
                    idx,
                    np.zeros(NUM_RESOURCES, np.int64),
                    resources_to_vector(usage),
                    True,
                    True,
                    False,
                ))
        if not rows:
            rows.append((
                -1,
                np.zeros(NUM_RESOURCES, np.int64),
                np.zeros(NUM_RESOURCES, np.int64),
                False,
                False,
                False,
            ))
        idxs, reqs, usages, has_metric, is_hp, is_lse = zip(*rows)
        return PodOvercommitInputs(
            node_idx=jnp.asarray(np.array(idxs, np.int32)),
            req=jnp.asarray(np.stack(reqs).astype(np.int32)),
            usage=jnp.asarray(np.stack(usages).astype(np.int32)),
            has_metric=jnp.asarray(np.array(has_metric, bool)),
            is_hp=jnp.asarray(np.array(is_hp, bool)),
            is_lse=jnp.asarray(np.array(is_lse, bool)),
            active=jnp.ones(len(rows), dtype=bool),
        )

    # -- reconcile ----------------------------------------------------------

    def reconcile_all(self, snapshot: ClusterSnapshot) -> List[NodeResourceUpdate]:
        """Compute every node's batch/mid allocatable; returns one update
        per node with the NeedSync decision already applied. Mutates the
        snapshot's NodeSpec.allocatable for synced nodes (the reference
        PATCHes Node.status.allocatable)."""
        if not snapshot.nodes:
            return []
        strategies = [
            self.config.strategy_for_node(n.labels) for n in snapshot.nodes
        ]
        updates: List[NodeResourceUpdate] = []

        # host plugins first: they may rewrite native allocatable
        # (cpu normalization) which feeds the array pass
        pre = [
            NodeResourceUpdate(n.name, {}, synced=False, degraded=False)
            for n in snapshot.nodes
        ]
        for plugin in self.plugins:
            for node, upd in zip(snapshot.nodes, pre):
                plugin.prepare(node, upd)

        node_index = {n.name: i for i, n in enumerate(snapshot.nodes)}
        nodes_in = self._lower_nodes(snapshot, strategies)
        pods_in = self._lower_pods(snapshot, node_index)

        # per-node strategy knobs as [N,...] arrays: node-selector
        # overrides cost nothing extra — still ONE fused dispatch
        n = len(snapshot.nodes)
        old_alloc = np.zeros((n, NUM_RESOURCES), np.int32)
        reclaim = np.zeros((n, NUM_RESOURCES), np.int32)
        mid_thr = np.zeros((n, NUM_RESOURCES), np.int32)
        cpu_pol = np.zeros(n, np.int32)
        mem_pol = np.zeros(n, np.int32)
        diff_thr = np.zeros(n, np.int32)
        enabled = np.zeros(n, bool)
        clamp_pct = lambda p: min(max(int(p), 0), 100)
        for i, (node, s) in enumerate(zip(snapshot.nodes, strategies)):
            for col in OVERCOMMIT_COLUMNS:
                old_alloc[i, col] = node.allocatable.get(col, 0)
            # clamp to [0, 100]: a malformed override must not produce
            # batch allocatable beyond node capacity (and the exact
            # percent identities assume pct <= 100)
            reclaim[i, ResourceName.CPU] = clamp_pct(
                s.cpu_reclaim_threshold_percent
            )
            reclaim[i, ResourceName.MEMORY] = clamp_pct(
                s.memory_reclaim_threshold_percent
            )
            mid_thr[i, ResourceName.CPU] = clamp_pct(s.mid_cpu_threshold_percent)
            mid_thr[i, ResourceName.MEMORY] = clamp_pct(
                s.mid_memory_threshold_percent
            )
            cpu_pol[i] = _POLICY_BY_NAME.get(
                s.cpu_calculate_policy, CalculatePolicy.USAGE
            )
            mem_pol[i] = _POLICY_BY_NAME.get(
                s.memory_calculate_policy, CalculatePolicy.USAGE
            )
            diff_thr[i] = int(round(s.resource_diff_threshold * 100))
            enabled[i] = s.enable

        params = OvercommitParams(
            reclaim_percent=jnp.asarray(reclaim),
            mid_threshold_percent=jnp.asarray(mid_thr),
            cpu_policy=jnp.asarray(cpu_pol),
            memory_policy=jnp.asarray(mem_pol),
        )
        alloc, sync = _overcommit_step(
            nodes_in, pods_in, params, jnp.asarray(old_alloc),
            jnp.asarray(diff_thr), jnp.asarray(enabled),
        )
        new_alloc = np.asarray(alloc)
        sync_mask = np.asarray(sync)

        fresh_np = np.asarray(nodes_in.metric_fresh)
        for i, node in enumerate(snapshot.nodes):
            upd = pre[i]
            upd.allocatable = {
                col: int(new_alloc[i, col]) for col in OVERCOMMIT_COLUMNS
            }
            upd.synced = bool(sync_mask[i])
            # Periodic force-update: even below the resource-diff
            # threshold, re-sync once update_time_threshold_seconds has
            # elapsed since the last write-back (reference:
            # batchresource NeedSync time gate, plugin.go isResourceDiff
            # || time since update > UpdateTimeThresholdSeconds).
            if not upd.synced and bool(enabled[i]):
                thr = strategies[i].update_time_threshold_seconds
                # first sighting baselines at now (no restart storm; the
                # diff gate covers genuinely unsynced nodes)
                last = self._last_sync.setdefault(node.name, snapshot.now)
                if thr > 0 and snapshot.now - last >= thr:
                    upd.synced = True
            upd.degraded = bool(enabled[i]) and not bool(fresh_np[i])
            if upd.synced:
                node.allocatable.update(upd.allocatable)
                self._last_sync[node.name] = snapshot.now
            updates.append(upd)
        # prune departed nodes so the map doesn't grow with cluster churn
        live = {n.name for n in snapshot.nodes}
        for name in [k for k in self._last_sync if k not in live]:
            del self._last_sync[name]
        return updates
