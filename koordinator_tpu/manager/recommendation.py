"""Recommendation controller: observed usage → right-sized requests.

Reference: the analysis.koordinator.sh API group
(apis/analysis/v1alpha1/recommendation_types.go:55) defines the object;
the usage statistics the status is computed from are exactly what the
koordlet prediction subsystem already maintains
(pkg/koordlet/prediction/peak_predictor.go: decaying histograms, p95 cpu
/ p98 memory peaks with a safety margin). This controller reuses that
machinery (:class:`PeakPredictServer`) at the cluster level:

- **observe**: fold every fresh NodeMetric's per-pod usage samples into
  one histogram bank per Recommendation target (workload owner-ref or
  pod label selector);
- **reconcile**: publish each Recommendation's peak estimate as its
  status on the bus;
- **consume**: :func:`wire_recommendation` keeps a PodMutatingWebhook's
  right-sizer pointed at the live Recommendation index, so admitted pods
  of a covered workload get their requests re-sized from observed usage
  (the VPA-shaped loop the reference's Recommendation API exists for).
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, Optional

from koordinator_tpu.apis.analysis import (
    CONDITION_NO_SAMPLES,
    CONDITION_READY,
    Recommendation,
)
from koordinator_tpu.apis.extension import ResourceName
from koordinator_tpu.apis.types import PodSpec, Resources
from koordinator_tpu.client.bus import APIServer, EventType, Kind
from koordinator_tpu.koordlet.prediction.predict_server import (
    PeakPredictServer,
    PredictionConfig,
)


class RecommendationController:
    """Cluster-level analysis over the bus (koord-manager component)."""

    def __init__(self, bus: APIServer,
                 config: Optional[PredictionConfig] = None, elector=None):
        self.bus = bus
        # one decaying-histogram bank, keyed by recommendation name —
        # the same estimator koordlet's predictor uses per pod
        self.server = PeakPredictServer(config)
        #: node -> update_time of the last NodeMetric folded in (samples
        #: are per report; re-reading an unchanged metric adds nothing)
        self._seen: Dict[str, float] = {}
        #: leader-elected deployments fence status writes — a deposed
        #: manager must not overwrite the leader's published numbers
        self.elector = elector

    # -- ingest --------------------------------------------------------------

    def observe(self, now: float) -> int:
        """Fold fresh NodeMetric pod samples into the target histograms;
        returns how many (pod, recommendation) samples were added."""
        recs = list(self.bus.list(Kind.RECOMMENDATION).values())
        if not recs:
            return 0
        pods = {p.uid: p for p in self.bus.list(Kind.POD).values()}
        added = 0
        for metric in self.bus.list(Kind.NODE_METRIC).values():
            if metric.update_time <= self._seen.get(metric.node_name, 0.0):
                continue
            self._seen[metric.node_name] = metric.update_time
            for uid, usage in metric.pod_usages.items():
                pod = pods.get(uid)
                if pod is None:
                    continue
                for rec in recs:
                    if not rec.target.matches(pod):
                        continue
                    self.server.update(
                        rec.name,
                        float(usage.get(ResourceName.CPU, 0)),
                        float(usage.get(ResourceName.MEMORY, 0)),
                        now,
                    )
                    added += 1
        return added

    # -- publish -------------------------------------------------------------

    def _publish(self, name: str, rec) -> None:
        if self.elector is not None:
            self.elector.fenced(
                lambda: self.bus.apply(Kind.RECOMMENDATION, name, rec)
            )
        else:
            self.bus.apply(Kind.RECOMMENDATION, name, rec)

    def reconcile(self, now: float) -> int:
        """Recompute every Recommendation's status and publish changed
        ones on the bus; returns how many were updated."""
        updated = 0
        for name, rec in self.bus.list(Kind.RECOMMENDATION).items():
            peak = self.server.peak(rec.name)
            if peak["cpu"] is None and peak["memory"] is None:
                # an empty LOCAL bank must not clobber a ready status a
                # previous leader published (post-failover warm-up) —
                # only never-computed recs get the NoSamples condition
                if rec.ready:
                    continue
                if not rec.conditions.get(CONDITION_NO_SAMPLES):
                    # publish a COPY: a fenced-off (deposed) write must
                    # leak nothing into the shared bus object
                    self._publish(name, dataclasses.replace(
                        rec,
                        conditions={CONDITION_NO_SAMPLES: True,
                                    CONDITION_READY: False},
                        update_time=now,
                    ))
                    updated += 1
                continue
            recommended: Resources = {}
            if peak["cpu"] is not None:
                recommended[ResourceName.CPU] = int(math.ceil(peak["cpu"]))
            if peak["memory"] is not None:
                recommended[ResourceName.MEMORY] = int(
                    math.ceil(peak["memory"])
                )
            # publish on value change OR when not yet consumable (a
            # pre-seeded recommended value without conditions must still
            # gain the Ready condition)
            if recommended != rec.recommended or not rec.ready:
                self._publish(name, dataclasses.replace(
                    rec,
                    recommended=recommended,
                    conditions={CONDITION_READY: True,
                                CONDITION_NO_SAMPLES: False},
                    update_time=now,
                ))
                updated += 1
        return updated

    def run_once(self, now: float) -> int:
        self.observe(now)
        return self.reconcile(now)


class RecommendationIndex:
    """Live read side: resolves a pod to its covering Recommendation
    (what the webhook right-sizer consumes)."""

    def __init__(self):
        self._recs: Dict[str, Recommendation] = {}

    def on_event(self, event: EventType, name: str, rec) -> None:
        if event is EventType.DELETED:
            self._recs.pop(name, None)
        else:
            self._recs[name] = rec

    def recommendation_for(self, pod: PodSpec) -> Optional[Resources]:
        for name in sorted(self._recs):
            rec = self._recs[name]
            if rec.ready and rec.target.matches(pod):
                return dict(rec.recommended)
        return None


def wire_recommendation(bus: APIServer, webhook=None,
                        config: Optional[PredictionConfig] = None,
                        elector=None):
    """Build the controller and (optionally) point a PodMutatingWebhook's
    right-sizer at the live index; returns the controller."""
    controller = RecommendationController(bus, config, elector)
    index = RecommendationIndex()
    bus.watch(Kind.RECOMMENDATION, index.on_event)
    if webhook is not None:
        webhook.recommendation_for = index.recommendation_for
    return controller
