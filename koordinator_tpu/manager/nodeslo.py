"""nodeslo controller: renders per-node NodeSLO specs from cluster config.

Reference: pkg/slo-controller/nodeslo/{nodeslo_controller.go,
resource_strategy.go, extender_plugin.go} — merges the cluster strategy
ConfigMaps (threshold, QoS, CPU burst, system) with node-selector
overrides into one NodeSLO CR per node, extensible via extender plugins.
"""

from __future__ import annotations

import copy
from typing import Callable, Dict, List, Optional, Tuple

from koordinator_tpu.apis.types import selector_matches
from koordinator_tpu.manager.sloconfig import (
    NodeSLOSpec,
    NodeStrategySelector,
    default_node_slo_spec,
    merge_overrides,
)

#: Extender plugin: (node_name, node_labels, spec) -> None, may mutate
#: spec.extensions (reference: nodeslo/extender_plugin.go
#: NodeSLOExtender interface).
NodeSLOExtender = Callable[[str, Dict[str, str], NodeSLOSpec], None]


#: A node-selector-scoped NodeSLO override — same selector + JSON-merge-
#: patch shape as the colocation node strategy (reference:
#: configuration.NodeStrategy in the nodeSLO ConfigMaps).
NodeSLOOverride = NodeStrategySelector


class NodeSLOController:
    """Renders NodeSLO specs: cluster default -> matching overrides ->
    extender plugins."""

    def __init__(
        self,
        cluster_spec: Optional[NodeSLOSpec] = None,
        overrides: Optional[List[NodeSLOOverride]] = None,
        extenders: Optional[List[NodeSLOExtender]] = None,
    ):
        self.cluster_spec = cluster_spec or default_node_slo_spec()
        self.overrides = overrides or []
        self.extenders = extenders or []

    def render(self, node_name: str, node_labels: Dict[str, str]) -> NodeSLOSpec:
        spec = copy.deepcopy(self.cluster_spec)
        for ov in self.overrides:
            if selector_matches(ov.match_labels, node_labels):
                spec = merge_overrides(spec, ov.overrides)
        for ext in self.extenders:
            ext(node_name, node_labels, spec)
        return spec

    def reconcile_all(
        self, nodes: List[Tuple[str, Dict[str, str]]]
    ) -> Dict[str, NodeSLOSpec]:
        return {name: self.render(name, labels) for name, labels in nodes}
