"""koord-manager: central controllers (noderesource overcommit, nodemetric
collect policy, nodeslo strategy rendering); the admission webhooks it
serves live in ``koordinator_tpu.webhook`` (wired via ``cmd.manager``).

Reference layout: cmd/koord-manager + pkg/slo-controller (§2.3 of
SURVEY.md). The reconcile loops here are batched: instead of one
controller-runtime Reconcile per node, the noderesource controller lowers
the whole cluster onto the array substrate and computes every node's
batch/mid allocatable in one fused XLA program
(koordinator_tpu.ops.overcommit).
"""

from koordinator_tpu.manager.sloconfig import (
    ColocationStrategy,
    NodeSLOSpec,
    default_node_slo_spec,
)
from koordinator_tpu.manager.noderesource import NodeResourceController
from koordinator_tpu.manager.nodemetric import node_metric_collect_policy
from koordinator_tpu.manager.nodeslo import NodeSLOController

__all__ = [
    "ColocationStrategy",
    "NodeSLOSpec",
    "default_node_slo_spec",
    "NodeResourceController",
    "node_metric_collect_policy",
    "NodeSLOController",
]
