"""Control plane: reconcile loops that close feedback onto declared
SLOs (reference: koord-manager's slo-controller — NodeSLO policy
continuously re-derived from declared specs + observed metrics).

The first resident is :mod:`koordinator_tpu.control.slo`'s
:class:`~koordinator_tpu.control.slo.ServingSLOController`: the serving
path's analog of the NodeSLO reconcile loop, turning the streaming
intake's static watermark/deadline/capacity flags into a closed loop
toward per-lane latency SLOs (docs/DESIGN.md §25)."""

from koordinator_tpu.control.slo import (  # noqa: F401
    KnobBounds,
    ServingSLOController,
    SLOSpec,
    replay_decisions,
)
