"""ServingSLOController: the serving path's NodeSLO reconcile loop.

Reference: koord-manager's slo-controller continuously re-derives node
policy from a DECLARED SLO plus OBSERVED metrics — policy is an output
of a reconcile loop, never a hand-tuned constant. The streaming serving
mode (scheduler/streaming.py, DESIGN §22) inverted that: its
watermark / lane-deadline / capacity knobs are static flags an operator
must retune per deployment and per load regime. This module closes the
loop (DESIGN §25):

- **Inputs** (one :meth:`ServingSLOController.observe` snapshot per
  reconcile): the rolling per-lane submit→bind p99 AND the folded
  shed/deadline-exceeded failure counts from
  ``PodTimelines.stats(window_s=)``, the current knob values, and the
  device observatory's compile counter + worst padding-waste ratio.
- **Policy** (:meth:`ServingSLOController.step` — a PURE function of
  the observation and the controller's own state): bounded, hysteretic,
  at most ONE knob moves per reconcile, and every move starts a
  cooldown. Priority order: a confirmed lane p99 breach tightens that
  lane's deadline (halving, floored — then the watermark halves
  instead); window shed pressure doubles intake capacity (capped);
  high padding waste while comfortably in-SLO doubles the watermark
  (batch amortization — one-way permitted only until the first
  latency-driven watermark cut); a sustained comfortably-under-target
  lane relaxes its deadline back toward the configured base. A relax
  that breaches burns its ceiling (the failed value is never retried),
  so total adjustments are bounded on the halving ladder — the loop
  cannot oscillate.
- **Auditability**: every decision is a typed record (trigger signal,
  observed value vs target, knob, old→new) in a bounded ring served on
  the debug mux (``/apis/v1/plugins/slo``) and stamped into
  flight-recorder dumps; the observation ring beside it makes the
  whole sequence **replay-deterministic** — :func:`replay_decisions`
  re-drives a fresh policy over the recorded observations and must
  reproduce the decision sequence bit-for-bit (property-tested).
- **HA**: the applied knob state is published (fenced while leading)
  as a ``Kind.NODE_SLO`` bus object; a promoted standby adopts it
  before its first round, so convergence survives failover
  (StreamingLoop.on_promoted → :meth:`ServingSLOController.
  on_promoted`).

Concurrency: the loop thread drives :meth:`maybe_reconcile`; the debug
mux and flight recorder read :meth:`status` / :meth:`flight_payload`.
``_lock`` guards the rings + policy state (graftcheck lock map); it is
never held across the gate/timeline/bus locks — observe and apply run
outside it.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from koordinator_tpu.metrics.components import (
    SLO_DECISIONS,
    SLO_LANE_P99_RATIO,
)
from koordinator_tpu.obs.timeline import LANES

#: decision trigger signals (bounded label domain — graftcheck
#: LABEL_DOMAINS pins these)
SIGNALS = ("p99-over", "p99-under", "shed-capacity", "padding-waste")
#: the knobs the controller may move (bounded label domain)
KNOBS = ("watermark", "deadline", "capacity")

#: the bus object carrying the applied knob state across failover
DEFAULT_STATE_NAME = "koord-serving-slo"


def _parse_lane_slo(spec) -> Optional[float]:
    """One lane's declared target: ``None``/``""`` (lane ungoverned),
    a float (p99 seconds), or the flag string ``"p99=0.02"``."""
    if spec is None or spec == "":
        return None
    if isinstance(spec, (int, float)):
        return float(spec)
    text = str(spec).strip()
    if "=" in text:
        key, _, value = text.partition("=")
        if key.strip() != "p99":
            raise ValueError(
                f"unknown SLO objective {key.strip()!r} (only p99=<s>)"
            )
        return float(value)
    return float(text)


@dataclasses.dataclass(frozen=True)
class SLOSpec:
    """Declared per-lane submit→bind p99 targets (seconds). ``None``
    leaves a lane ungoverned — its knobs still move when OTHER signals
    (shed, padding) fire, but no latency target is enforced."""

    system: Optional[float] = None
    ls: Optional[float] = None
    be: Optional[float] = None

    @classmethod
    def parse(cls, system=None, ls=None, be=None) -> "SLOSpec":
        """Build from ``--slo-{system,ls,be}`` flag strings
        (``"p99=0.02"`` or a bare float literal)."""
        return cls(
            system=_parse_lane_slo(system),
            ls=_parse_lane_slo(ls),
            be=_parse_lane_slo(be),
        )

    def target(self, lane: str) -> Optional[float]:
        return getattr(self, lane)

    def any(self) -> bool:
        return any(self.target(lane) is not None for lane in LANES)

    def as_dict(self) -> Dict[str, Optional[float]]:
        return {lane: self.target(lane) for lane in LANES}


@dataclasses.dataclass(frozen=True)
class KnobBounds:
    """Hard actuator bounds — the controller NEVER steps a knob past
    these, whatever the signals say."""

    watermark_min: int = 1
    watermark_max: int = 4096
    #: the deadline halving floor: below this a round per pod is
    #: already firing as fast as the dispatch path can go
    deadline_floor_s: float = 0.0005
    capacity_max: int = 65536


class ServingSLOController:
    """The reconcile loop closing declared per-lane SLOs onto the
    streaming knobs. See the module docstring for the contract;
    ``loop=None`` builds a policy-only instance (what
    :func:`replay_decisions` drives)."""

    def __init__(self, loop=None, spec: SLOSpec = SLOSpec(),
                 *, bounds: KnobBounds = KnobBounds(),
                 bus=None, elector=None,
                 state_name: str = DEFAULT_STATE_NAME,
                 clock: Callable[[], float] = time.monotonic,
                 window_s: float = 5.0,
                 reconcile_interval_s: float = 0.25,
                 cooldown_s: float = 1.0,
                 min_samples: int = 8,
                 breach_rounds: int = 2,
                 relax_rounds: int = 8,
                 relax_frac: float = 0.5,
                 waste_threshold: float = 0.5,
                 ring_capacity: int = 256,
                 observation_capacity: int = 2048,
                 device=None, log: Callable = print):
        self._loop = loop
        self.spec = spec
        self.bounds = bounds
        self.bus = bus
        self.elector = elector
        self.state_name = state_name
        self._clock = clock
        self.window_s = window_s
        self.reconcile_interval_s = reconcile_interval_s
        self.cooldown_s = cooldown_s
        self.min_samples = min_samples
        self.breach_rounds = breach_rounds
        self.relax_rounds = relax_rounds
        self.relax_frac = relax_frac
        self.waste_threshold = waste_threshold
        self._log = log
        if device is None:
            from koordinator_tpu.obs.device import DEVICE_OBS

            device = DEVICE_OBS
        self._device = device
        #: the relax ceiling starts at the CONFIGURED base deadline —
        #: the controller tightens below it and relaxes back toward
        #: it, never above (captured at attach, before any retune)
        base = (None if loop is None
                else tuple(loop.cfg.lane_deadline_s))
        self._lock = threading.Lock()
        #: typed decision records, bounded (the debug-mux/flight ring)
        self._ring: deque = deque(maxlen=ring_capacity)
        #: one observation per reconcile — the replay substrate
        self._obs_ring: deque = deque(maxlen=observation_capacity)
        self._decisions_total = 0
        self._last_reconcile_at: Optional[float] = None
        self._adopted = False
        # -- pure policy state (advanced only by step()) -----------------
        self._seq = 0
        self._breach = {lane: 0 for lane in LANES}
        self._under = {lane: 0 for lane in LANES}
        #: per-lane max deadline a relax may reach; a relax whose value
        #: then breaches BURNS this down to the tightened value, so the
        #: failed rung is never retried (the anti-oscillation bound)
        self._relax_cap = {
            lane: (base[i] if base is not None else None)
            for i, lane in enumerate(LANES)
        }
        self._last_relax: Dict[str, float] = {}
        #: padding-driven watermark raises are permitted only until the
        #: first latency-driven watermark cut (one-way ratchet)
        self._wm_raise_ok = True
        self._last_decision_now: Optional[float] = None

    # -- observation ---------------------------------------------------------

    def observe(self, now: Optional[float] = None) -> dict:
        """Snapshot every input the policy is allowed to see. The
        returned dict is the WHOLE truth for :meth:`step` — replaying
        recorded observations reproduces the decisions bit-for-bit."""
        at = self._clock() if now is None else now
        knobs = self._knobs()
        lanes: Dict[str, dict] = {}
        timelines = getattr(getattr(self._loop, "scheduler", None),
                            "timelines", None)
        if timelines is not None:
            stats = timelines.stats(window_s=self.window_s)
            for lane in LANES:
                st = stats.get(lane)
                if st is not None:
                    lanes[lane] = {
                        "count": st["count"],
                        "p99_s": st["p99_s"],
                        "shed": dict(st.get("shed", {})),
                    }
        device = {"compiles": 0, "padding_waste": 0.0}
        if self._device is not None:
            try:
                device = {
                    "compiles": self._device.mark()["compiles"],
                    "padding_waste": self._device.padding_waste(),
                }
            except Exception:
                pass
        with self._lock:
            self._seq += 1
            seq = self._seq
        return {
            "seq": seq,
            "now": at,
            "window_s": self.window_s,
            "lanes": lanes,
            "knobs": knobs,
            "device": device,
        }

    def _knobs(self) -> dict:
        if self._loop is None:
            return {}
        cfg = self._loop.cfg
        return {
            "watermark": cfg.watermark,
            "lane_deadline_s": list(cfg.lane_deadline_s),
            "capacity": cfg.capacity,
        }

    # -- the pure policy step ------------------------------------------------

    def step(self, obs: dict) -> Optional[dict]:
        """Advance the policy on one observation; returns at most one
        typed decision (NOT yet applied). Pure with respect to
        ``(obs, controller state)`` — no clocks, no gate, no bus —
        which is what makes the decision log replay-deterministic."""
        with self._lock:
            return self._step_locked(obs)

    def _step_locked(self, obs: dict) -> Optional[dict]:
        lanes = obs.get("lanes", {})
        knobs = obs.get("knobs", {})
        deadlines = list(knobs.get("lane_deadline_s", ()))
        if not deadlines:
            return None

        def lane_shed(st: dict) -> int:
            return sum(st.get("shed", {}).values())

        # 1. streak bookkeeping — EVERY reconcile, cooldown or not:
        # hysteresis counts consecutive confirmations, and a cooldown
        # window's observations still confirm or refute
        for i, lane in enumerate(LANES):
            target = self.spec.target(lane)
            if target is None:
                continue
            st = lanes.get(lane)
            sampled = (st is not None and st["count"] >= self.min_samples
                       and st["p99_s"] is not None)
            breached = sampled and st["p99_s"] > target
            under = (sampled and st["p99_s"] <= self.relax_frac * target
                     and lane_shed(st) == 0)
            self._breach[lane] = self._breach[lane] + 1 if breached else 0
            self._under[lane] = self._under[lane] + 1 if under else 0
        # 2. cooldown: one knob per window, hysteresis keeps counting
        if (self._last_decision_now is not None
                and obs["now"] - self._last_decision_now
                < self.cooldown_s):
            return None

        def decide(signal: str, lane: Optional[str], knob: str,
                   observed, target, old, new) -> dict:
            self._last_decision_now = obs["now"]
            return {
                "seq": obs["seq"],
                "now": obs["now"],
                "signal": signal,
                "lane": lane,
                "knob": knob,
                "observed": observed,
                "target": target,
                "old": old,
                "new": new,
            }

        # 3. confirmed p99 breach: tighten that lane's deadline
        # (system outranks ls outranks be), then the watermark
        for i, lane in enumerate(LANES):
            target = self.spec.target(lane)
            if target is None or self._breach[lane] < self.breach_rounds:
                continue
            observed = lanes[lane]["p99_s"]
            old_d = deadlines[i]
            new_d = max(self.bounds.deadline_floor_s, old_d / 2.0)
            if new_d < old_d:
                if abs(self._last_relax.get(lane, -1.0) - old_d) < 1e-12:
                    # this value was reached by a relax and breached:
                    # burn the ceiling so it is never retried
                    self._relax_cap[lane] = new_d
                self._breach[lane] = 0
                return decide("p99-over", lane, "deadline",
                              observed, target, old_d, new_d)
            watermark = knobs.get("watermark", 0)
            if watermark > self.bounds.watermark_min:
                new_w = max(self.bounds.watermark_min, watermark // 2)
                self._wm_raise_ok = False
                self._breach[lane] = 0
                return decide("p99-over", lane, "watermark",
                              observed, target, watermark, new_w)
            # both actuators floored: nothing left to tighten
            self._breach[lane] = 0
        # 4. window shed pressure: the intake is refusing arrivals —
        # grow it (bounded; BE-first shedding still protects the lanes)
        shed_cap = sum(
            st.get("shed", {}).get("capacity", 0)
            for st in lanes.values()
        )
        capacity = knobs.get("capacity", 0)
        if shed_cap > 0 and capacity < self.bounds.capacity_max:
            new_c = min(self.bounds.capacity_max, capacity * 2)
            return decide("shed-capacity", None, "capacity",
                          shed_cap, 0, capacity, new_c)
        # 5. padding waste while comfortably in-SLO: bigger batches
        # fill the pow2 buckets (one-way: never after a latency-driven
        # watermark cut)
        waste = obs.get("device", {}).get("padding_waste", 0.0)
        watermark = knobs.get("watermark", 0)
        in_slo = all(
            self._breach[lane] == 0
            and (lanes.get(lane) is None
                 or lanes[lane]["p99_s"] is None
                 or lanes[lane]["p99_s"] <= self.spec.target(lane))
            for lane in LANES if self.spec.target(lane) is not None
        )
        if (self._wm_raise_ok and waste > self.waste_threshold
                and shed_cap == 0 and in_slo
                and watermark < self.bounds.watermark_max):
            new_w = min(self.bounds.watermark_max, watermark * 2)
            return decide("padding-waste", None, "watermark",
                          waste, self.waste_threshold, watermark, new_w)
        # 6. sustained comfortably-under: relax the most-expendable
        # tightened lane back toward its base (be first — relaxing the
        # strictest lane last), capped by the (possibly burned) ceiling
        for i, lane in reversed(list(enumerate(LANES))):
            target = self.spec.target(lane)
            cap = self._relax_cap[lane]
            if (target is None or cap is None
                    or self._under[lane] < self.relax_rounds):
                continue
            old_d = deadlines[i]
            new_d = min(cap, old_d * 2.0)
            if new_d > old_d:
                self._last_relax[lane] = new_d
                self._under[lane] = 0
                return decide("p99-under", lane, "deadline",
                              lanes[lane]["p99_s"], target, old_d, new_d)
        return None

    # -- reconcile (the loop thread) -----------------------------------------

    def maybe_reconcile(self, now: Optional[float] = None
                        ) -> Optional[dict]:
        """Reconcile if the interval elapsed (the StreamingLoop calls
        this every pump/trigger iteration)."""
        return self.reconcile(now=now, force=False)

    def reconcile(self, now: Optional[float] = None,
                  force: bool = True) -> Optional[dict]:
        """One observe → step → apply → record pass. Returns the
        applied decision (None when held)."""
        at = self._clock() if now is None else now
        with self._lock:
            if (not force and self._last_reconcile_at is not None
                    and at - self._last_reconcile_at
                    < self.reconcile_interval_s):
                return None
            self._last_reconcile_at = at
        obs = self.observe(now=at)
        with self._lock:
            self._obs_ring.append(obs)
            decision = self._step_locked(obs)
            if decision is not None:
                self._ring.append(decision)
                self._decisions_total += 1
        if decision is not None:
            self._apply(decision)
            self._publish_state(obs["seq"])
            SLO_DECISIONS.inc({
                "knob": decision["knob"], "signal": decision["signal"],
            })
            self._log(
                f"slo: {decision['signal']} "
                f"lane={decision['lane']} {decision['knob']} "
                f"{decision['old']} -> {decision['new']} "
                f"(observed {decision['observed']} vs "
                f"target {decision['target']})"
            )
        self._publish_gauges(obs)
        return decision

    def _apply(self, decision: dict) -> None:
        if self._loop is None:
            return
        gate = self._loop.gate
        knob = decision["knob"]
        if knob == "watermark":
            gate.retune(watermark=decision["new"])
        elif knob == "capacity":
            gate.retune(capacity=decision["new"])
        elif knob == "deadline":
            lane_idx = LANES.index(decision["lane"])
            deadlines = list(gate.cfg.lane_deadline_s)
            deadlines[lane_idx] = decision["new"]
            gate.retune(lane_deadline_s=tuple(deadlines))

    def _publish_gauges(self, obs: dict) -> None:
        for lane in LANES:
            target = self.spec.target(lane)
            st = obs.get("lanes", {}).get(lane)
            if target is None or st is None or st["p99_s"] is None:
                continue
            SLO_LANE_P99_RATIO.set(st["p99_s"] / target, {"lane": lane})

    # -- HA: knob-state handoff over the bus ---------------------------------

    def _publish_state(self, seq: int) -> None:
        """Publish the applied knob state as a ``Kind.NODE_SLO`` bus
        object (the reference slo-controller's output object), fenced
        while leading — a deposed zombie's late publish must not
        clobber the new leader's convergence."""
        if self.bus is None:
            return
        from koordinator_tpu.client.bus import Kind

        state = {"seq": seq, "knobs": self._knobs(),
                 "decisions_total": self.decisions_total()}

        def _apply_state():
            self.bus.apply(Kind.NODE_SLO, self.state_name, state)

        if self.elector is not None:
            from koordinator_tpu.client.leaderelection import FencingError

            try:
                self.elector.fenced(_apply_state)
            except FencingError:
                self._log("slo: knob-state publish fenced "
                          "(lease lost); dropping")
        else:
            _apply_state()

    def on_promoted(self) -> bool:
        """Adopt the previous leader's published knob state (called
        from StreamingLoop.on_promoted before the intake sweep).
        Returns True when state was adopted."""
        if self.bus is None or self._loop is None:
            return False
        from koordinator_tpu.client.bus import Kind

        state = self.bus.get(Kind.NODE_SLO, self.state_name)
        if not state:
            return False
        knobs = state.get("knobs", {})
        self._loop.gate.retune(
            watermark=knobs.get("watermark"),
            lane_deadline_s=(
                tuple(knobs["lane_deadline_s"])
                if knobs.get("lane_deadline_s") else None
            ),
            capacity=knobs.get("capacity"),
        )
        with self._lock:
            self._adopted = True
        self._log(f"slo: adopted knob state seq={state.get('seq')} "
                  f"on promotion")
        return True

    # -- read side -----------------------------------------------------------

    def decisions_total(self) -> int:
        with self._lock:
            return self._decisions_total

    def decisions(self) -> List[dict]:
        with self._lock:
            return [dict(d) for d in self._ring]

    def observations(self) -> List[dict]:
        with self._lock:
            return [dict(o) for o in self._obs_ring]

    def status(self) -> dict:
        """Debug-mux payload (registered as ``slo``): the declared
        spec, live knobs, policy state, and the decision-ring tail."""
        with self._lock:
            ring = [dict(d) for d in list(self._ring)[-32:]]
            total = self._decisions_total
            adopted = self._adopted
            policy = {
                "breach": dict(self._breach),
                "under": dict(self._under),
                "relax_cap": dict(self._relax_cap),
                "wm_raise_ok": self._wm_raise_ok,
            }
        return {
            "spec": self.spec.as_dict(),
            "knobs": self._knobs(),
            "window_s": self.window_s,
            "cooldown_s": self.cooldown_s,
            "decisions_total": total,
            "adopted_state": adopted,
            "policy": policy,
            "decisions": ring,
        }

    def flight_payload(self) -> dict:
        """The flight recorder's ``slo`` section: what was the policy
        doing when the anomaly dumped."""
        with self._lock:
            ring = [dict(d) for d in list(self._ring)[-16:]]
            total = self._decisions_total
        return {
            "spec": self.spec.as_dict(),
            "knobs": self._knobs(),
            "decisions_total": total,
            "decisions": ring,
        }


def replay_decisions(spec: SLOSpec, observations: List[dict],
                     *, bounds: KnobBounds = KnobBounds(),
                     base_deadlines: Optional[Tuple[float, ...]] = None,
                     **params) -> List[dict]:
    """Re-drive a FRESH policy over recorded observations; the returned
    decision list must equal the original controller's decision ring
    bit-for-bit (the replay-determinism contract — decisions depend
    only on observations, never on wall clocks or live state).
    ``base_deadlines`` seeds the relax ceilings the live controller
    captured from its loop's configured base; pass the same values the
    original saw (defaults to the first observation's knobs)."""
    ctl = ServingSLOController(loop=None, spec=spec, bounds=bounds,
                               log=lambda *_a, **_k: None, **params)
    if base_deadlines is None and observations:
        base_deadlines = tuple(
            observations[0].get("knobs", {}).get("lane_deadline_s", ())
        ) or None
    if base_deadlines is not None:
        ctl._relax_cap = {
            lane: base_deadlines[i] for i, lane in enumerate(LANES)
        }
    out: List[dict] = []
    for obs in observations:
        decision = ctl.step(obs)
        if decision is not None:
            out.append(decision)
    return out
