"""The arbitrated eviction control plane (docs/DESIGN.md §27).

Reference: the Koordinator descheduler's MigrationController arbitration
(pkg/descheduler/controllers/migration: filter chain + per-node /
per-namespace eviction quotas + workload max-unavailable), which exists
so that re-placement pressure — however many strategies generate it —
never becomes an outage. Our repro has four independent eviction
sources: the device preemption solve (§24), the operator-called
``defrag_headroom`` API, the LoadAware rebalance sweep
(``descheduler/loadaware.py``), and working-set demotions (§26). The
:class:`MigrationArbiter` is the single choke point all of them pass
through before a victim is actually evicted.

Contract (mirrors the quota semantics of the reference's
``arbitrator`` + ``EvictionLimiter``):

- Declared disruption budgets: per-node, per-tenant (QoS lane), and
  per-round eviction caps, each over a rolling ``window_s`` window,
  plus a per-node cooldown after an admitted eviction and a gang
  min-available guard (a request may carry per-gang headroom — how many
  more members the gang can lose before violating ``min_member``).
- Over-budget requests are **deferred with a typed, counted refusal**
  — never dropped silently: the caller gets the admitted prefix and a
  ``(uid, reason)`` list for the rest, every deferral lands in the
  ``scheduler_migration_deferrals_total{source,reason}`` counter, and
  the whole decision is a typed record in a bounded ring.
- ``dry_run`` classifies without acting: the verdict reports what WOULD
  be admitted, ``apply`` is False, and no window bookkeeping commits.
- The unlimited default budget admits everything with zero bookkeeping
  effects beyond the record — every legacy path stays bit-identical.
- Working-set demotions are **undeferrable**: demotion is the memory
  safety valve (refusing one trades an SLO wobble for an OOM), so they
  flow through :meth:`MigrationArbiter.note` — recorded and counted
  against the same windows, never deferred.

Replay determinism: like the SLO controller (§25), decisions must
re-derive bit-for-bit from the recorded requests alone —
:func:`replay_requests` re-drives a fresh arbiter over a recorded ring
and the chaos suite asserts equality. No wall clock or ambient
randomness may leak into the policy; ``now`` is injected (ctor
``clock`` for defaults, explicit per call for the schedulers).

:class:`DefragController` closes the loop on ``defrag_headroom``: the
reconcile-on-the-pump pattern from ``control/slo.py`` watching a
fragmentation signal — the largest schedulable hole vs the smallest
pending gang's member demand — and applying ONE bounded repack per
cooldown through the arbiter, with hysteretic confirmation against
thrash.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from collections import deque
from typing import Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import numpy as np

from koordinator_tpu.metrics.components import (
    DEFRAG_DECISIONS,
    MIGRATION_ADMITTED,
    MIGRATION_DEFERRALS,
    MIGRATION_REQUESTS,
)

#: every eviction source that may pass through the arbiter — the
#: ``source`` metric label domain (graftcheck metrics-hygiene audits
#: this enumeration against the emit sites)
SOURCES = ("preemption", "defrag", "rebalance", "workingset")

#: every typed deferral reason, in CHECK PRECEDENCE ORDER: a victim
#: violating several budgets is counted under the first — the
#: ``reason`` values the deferral counter may emit
REASONS = ("cooldown", "round-budget", "node-budget", "tenant-budget",
           "gang-min-available")


@dataclasses.dataclass(frozen=True)
class MigrationBudget:
    """Declared disruption budgets. ``None`` caps are unlimited; the
    all-``None`` default is the bit-identical legacy configuration
    (every request admits in full, no cooldowns, no deferral)."""

    #: admitted evictions per scheduling round (all sources combined)
    max_per_round: Optional[int] = None
    #: admitted evictions per node within ``window_s``
    max_per_node: Optional[int] = None
    #: admitted evictions per tenant/QoS lane within ``window_s``
    max_per_tenant: Optional[int] = None
    #: rolling budget window in seconds
    window_s: float = 60.0
    #: per-node quiet period after an admitted eviction on that node
    node_cooldown_s: float = 0.0
    #: classify-only mode: verdicts report, nothing commits
    dry_run: bool = False

    @property
    def unlimited(self) -> bool:
        return (
            self.max_per_round is None
            and self.max_per_node is None
            and self.max_per_tenant is None
            and self.node_cooldown_s <= 0.0
            and not self.dry_run
        )


class MigrationVerdict(NamedTuple):
    """One request's outcome: the admitted prefix (in request order),
    the typed deferrals, and whether the caller may act (``apply`` is
    False under ``dry_run``)."""

    admitted: Tuple[str, ...]
    deferred: Tuple[Tuple[str, str], ...]   # (uid, reason)
    apply: bool
    record: dict


class MigrationArbiter:
    """The choke point. Thread contract: schedulers request from loop
    threads, the chaos saboteur squeezes budgets from test drivers,
    debug-mux/flight readers snapshot the rings — one ``_lock`` over
    the budget, every window deque, and both bounded rings. The lock
    is a leaf: nothing is called out to while holding it."""

    def __init__(
        self,
        budget: Optional[MigrationBudget] = None,
        clock: Callable[[], float] = time.monotonic,
        ring_capacity: int = 512,
    ):
        self._clock = clock
        self._lock = threading.Lock()
        self._budget = budget or MigrationBudget()
        #: bounded decision ring: every verdict, replay-deterministic
        self._ring: deque = deque(maxlen=ring_capacity)
        #: admitted-eviction timestamps per node / lane / gang (purged
        #: past ``window_s``)
        self._node_times: Dict[str, deque] = {}
        self._lane_times: Dict[str, deque] = {}
        self._gang_times: Dict[str, deque] = {}
        #: last admitted-eviction time per node (cooldown gate)
        self._node_last: Dict[str, float] = {}
        #: the current round key + its admitted count
        self._round_key: Optional[int] = None
        self._round_count = 0
        self._requests_total = 0
        self._admitted_total = 0
        self._deferred_total = 0
        self._deferred_reasons: Dict[str, int] = {}
        self._seq = 0

    # -- budget ---------------------------------------------------------------

    def set_budget(self, budget: MigrationBudget) -> None:
        """Swap the declared budget live (operator retune, or the
        chaos ``budget-squeeze-mid-wave`` fault). Window history is
        KEPT: a squeeze mid-wave judges the new caps against the
        evictions already admitted in the window."""
        with self._lock:
            self._budget = budget

    def budget(self) -> MigrationBudget:
        with self._lock:
            return self._budget

    def begin_round(self, round_key: int) -> None:
        """Start a scheduling round: the per-round cap counts admitted
        evictions (all sources) until the next ``begin_round``."""
        with self._lock:
            if round_key != self._round_key:
                self._round_key = round_key
                self._round_count = 0

    # -- the decision ---------------------------------------------------------

    def request(
        self,
        source: str,
        node: Optional[str],
        uids: Sequence[str],
        lanes: Optional[Sequence[Optional[str]]] = None,
        gangs: Optional[Sequence[Optional[str]]] = None,
        gang_headroom: Optional[Dict[str, int]] = None,
        now: Optional[float] = None,
        all_or_nothing: bool = False,
    ) -> MigrationVerdict:
        """Arbitrate one eviction batch. ``uids`` are judged in order
        (partial admission: the caller evicts exactly the admitted
        list). ``lanes[i]``/``gangs[i]`` annotate victim i;
        ``gang_headroom[g]`` is how many more members gang ``g`` may
        lose before violating its min-available. ``all_or_nothing``
        defers the WHOLE batch when any member would be deferred (the
        preemption contract: a preemptor's victim set is indivisible —
        a partial evict would burn budget without freeing the hole)."""
        if source not in SOURCES:
            raise ValueError(f"unknown migration source {source!r}")
        uids = tuple(uids)
        lanes = tuple(lanes) if lanes is not None else (None,) * len(uids)
        gangs = tuple(gangs) if gangs is not None else (None,) * len(uids)
        if len(lanes) != len(uids) or len(gangs) != len(uids):
            raise ValueError("lanes/gangs must align with uids")
        if now is None:
            now = self._clock()
        with self._lock:
            return self._request_locked(
                source, node, uids, lanes, gangs,
                dict(gang_headroom or {}), float(now), all_or_nothing,
            )

    def _request_locked(self, source, node, uids, lanes, gangs,
                        gang_headroom, now, all_or_nothing):
        budget = self._budget
        self._purge_locked(now)
        admitted: List[str] = []
        admitted_lanes: List[Optional[str]] = []
        admitted_gangs: List[Optional[str]] = []
        deferred: List[Tuple[str, str]] = []
        # tentative in-request increments so one batch can't overshoot
        lane_inc: Dict[str, int] = {}
        gang_inc: Dict[str, int] = {}
        node_inc = 0
        for uid, lane, gang in zip(uids, lanes, gangs):
            reason = self._refusal_locked(
                budget, now, node, lane, gang, gang_headroom,
                node_inc, lane_inc.get(lane, 0), gang_inc.get(gang, 0),
                len(admitted),
            )
            if reason is None:
                admitted.append(uid)
                admitted_lanes.append(lane)
                admitted_gangs.append(gang)
                node_inc += 1
                if lane is not None:
                    lane_inc[lane] = lane_inc.get(lane, 0) + 1
                if gang is not None:
                    gang_inc[gang] = gang_inc.get(gang, 0) + 1
            else:
                deferred.append((uid, reason))
        if all_or_nothing and deferred:
            # the batch refusal is typed by the FIRST violation; members
            # that would have been admitted defer under the same reason
            reason = deferred[0][1]
            deferred = [(uid, reason) for uid in uids]
            admitted, admitted_lanes, admitted_gangs = [], [], []
        apply = not budget.dry_run
        if apply and admitted:
            for lane, gang in zip(admitted_lanes, admitted_gangs):
                self._commit_locked(now, node, lane, gang)
            self._round_count += len(admitted)
        self._seq += 1
        record = {
            "seq": self._seq,
            "now": now,
            "source": source,
            "node": node,
            "round": self._round_key,
            "uids": list(uids),
            "lanes": list(lanes),
            "gangs": list(gangs),
            "gang_headroom": dict(gang_headroom),
            "all_or_nothing": bool(all_or_nothing),
            "admitted": list(admitted),
            "deferred": [{"uid": u, "reason": r} for u, r in deferred],
            "dry_run": budget.dry_run,
        }
        self._ring.append(record)
        self._requests_total += len(uids)
        if apply:
            self._admitted_total += len(admitted)
        self._deferred_total += len(deferred)
        for _, r in deferred:
            self._deferred_reasons[r] = self._deferred_reasons.get(r, 0) + 1
        MIGRATION_REQUESTS.inc({"source": source}, len(uids))
        if apply and admitted:
            MIGRATION_ADMITTED.inc({"source": source}, len(admitted))
        for _, r in deferred:
            MIGRATION_DEFERRALS.inc({"source": source, "reason": r})
        return MigrationVerdict(
            tuple(admitted), tuple(deferred), apply, record
        )

    def _refusal_locked(self, budget, now, node, lane, gang,
                        gang_headroom, node_inc, lane_n, gang_n,
                        batch_admitted):
        """The typed refusal for ONE victim, or None to admit — checks
        in REASONS precedence order, counting both the committed window
        state and this batch's tentative admissions."""
        if budget.node_cooldown_s > 0.0 and node is not None:
            last = self._node_last.get(node)
            # a within-batch admission also arms the cooldown: one
            # admitted victim per node per request under a cooldown
            if node_inc > 0 or (
                last is not None and now - last < budget.node_cooldown_s
            ):
                return "cooldown"
        if budget.max_per_round is not None:
            if self._round_count + batch_admitted >= budget.max_per_round:
                return "round-budget"
        if budget.max_per_node is not None and node is not None:
            have = len(self._node_times.get(node, ())) + node_inc
            if have >= budget.max_per_node:
                return "node-budget"
        if budget.max_per_tenant is not None and lane is not None:
            have = len(self._lane_times.get(lane, ())) + lane_n
            if have >= budget.max_per_tenant:
                return "tenant-budget"
        if gang is not None and gang in gang_headroom:
            lost = len(self._gang_times.get(gang, ())) + gang_n
            if lost >= max(int(gang_headroom[gang]), 0):
                return "gang-min-available"
        return None

    def _commit_locked(self, now, node, lane, gang) -> None:
        if node is not None:
            self._node_times.setdefault(node, deque()).append(now)
            self._node_last[node] = now
        if lane is not None:
            self._lane_times.setdefault(lane, deque()).append(now)
        if gang is not None:
            self._gang_times.setdefault(gang, deque()).append(now)

    def _purge_locked(self, now: float) -> None:
        horizon = now - self._budget.window_s
        for times in (self._node_times, self._lane_times,
                      self._gang_times):
            for key in list(times):
                dq = times[key]
                while dq and dq[0] <= horizon:
                    dq.popleft()
                if not dq:
                    del times[key]

    # -- the undeferrable source ---------------------------------------------

    def note(self, source: str, node: Optional[str], uids: Sequence[str],
             lanes: Optional[Sequence[Optional[str]]] = None,
             now: Optional[float] = None) -> None:
        """Record an eviction that already happened and MUST happen
        (working-set demotions: the memory-pressure safety valve —
        refusing one trades an SLO wobble for an OOM). Counted against
        the same windows so budget views stay whole-truth; never
        deferred."""
        if source not in SOURCES:
            raise ValueError(f"unknown migration source {source!r}")
        uids = tuple(uids)
        lanes = tuple(lanes) if lanes is not None else (None,) * len(uids)
        if now is None:
            now = self._clock()
        with self._lock:
            self._purge_locked(now)
            for lane in lanes:
                self._commit_locked(float(now), node, lane, None)
            self._seq += 1
            record = {
                "seq": self._seq,
                "now": float(now),
                "source": source,
                "node": node,
                "round": self._round_key,
                "uids": list(uids),
                "lanes": list(lanes),
                "gangs": [None] * len(uids),
                "gang_headroom": {},
                "all_or_nothing": False,
                "admitted": list(uids),
                "deferred": [],
                "dry_run": False,
                "undeferrable": True,
            }
            self._ring.append(record)
            self._requests_total += len(uids)
            self._admitted_total += len(uids)
        MIGRATION_REQUESTS.inc({"source": source}, len(uids))
        MIGRATION_ADMITTED.inc({"source": source}, len(uids))

    # -- observability --------------------------------------------------------

    def decisions(self) -> List[dict]:
        with self._lock:
            return list(self._ring)

    def status(self) -> dict:
        """The debug-mux ``migration`` service payload."""
        with self._lock:
            budget = self._budget
            return {
                "budget": dataclasses.asdict(budget),
                "unlimited": budget.unlimited,
                "requests_total": self._requests_total,
                "admitted_total": self._admitted_total,
                "deferred_total": self._deferred_total,
                "deferred_by_reason": dict(self._deferred_reasons),
                "round": self._round_key,
                "round_admitted": self._round_count,
                "window_nodes": {
                    k: len(v) for k, v in self._node_times.items()
                },
                "window_lanes": {
                    k: len(v) for k, v in self._lane_times.items()
                },
                "decisions": list(self._ring)[-16:],
            }

    def flight_payload(self) -> dict:
        """Flight-recorder hook: the compact decision tail."""
        with self._lock:
            return {
                "deferred_total": self._deferred_total,
                "deferred_by_reason": dict(self._deferred_reasons),
                "decisions": list(self._ring)[-32:],
            }


def replay_requests(budget: MigrationBudget,
                    records: Sequence[dict]) -> List[dict]:
    """Re-drive a fresh arbiter over a recorded decision ring and
    return the re-derived records: the replay-determinism contract is
    that they equal the originals field-for-field (modulo ``seq``
    origin, which restarts at 1 — compare rings recorded from a fresh
    arbiter). ``begin_round`` transitions are reconstructed from the
    recorded ``round`` keys; undeferrable notes replay as notes."""
    fresh = MigrationArbiter(budget=budget, clock=lambda: 0.0)
    out: List[dict] = []
    for rec in records:
        if rec.get("round") is not None:
            fresh.begin_round(rec["round"])
        if rec.get("undeferrable"):
            fresh.note(rec["source"], rec["node"], rec["uids"],
                       lanes=rec["lanes"], now=rec["now"])
        else:
            fresh.request(
                rec["source"], rec["node"], rec["uids"],
                lanes=rec["lanes"], gangs=rec["gangs"],
                gang_headroom=rec.get("gang_headroom") or {},
                now=rec["now"],
                all_or_nothing=rec.get("all_or_nothing", False),
            )
        out.append(fresh.decisions()[-1])
    return out


# -- the closed defrag loop ---------------------------------------------------


@dataclasses.dataclass(frozen=True)
class DefragPolicy:
    """The defrag controller's declared behavior (all knobs bounded,
    mirroring the SLO controller's shape)."""

    #: reconcile cadence gate (maybe_reconcile no-ops inside it)
    interval_s: float = 5.0
    #: quiet period between applied repacks: ONE bounded action per
    #: cooldown
    cooldown_s: float = 30.0
    #: hysteresis: consecutive fragmented observations before acting
    confirm: int = 2
    #: classify and record without calling defrag_headroom
    dry_run: bool = False


class DefragController:
    """Close the loop on ``defrag_headroom`` (docs/DESIGN.md §27).

    Reconcile-on-the-pump (the §25 pattern): each reconcile observes
    the whole truth — the fragmentation signal is *largest schedulable
    hole vs pending gang demand*: a pending gang whose member shape
    fits NO schedulable node even though aggregate free capacity could
    hold it is fragmentation the repack can fix. The pure policy step
    (streak + confirm + cooldown) then decides at most one action; the
    action is ``scheduler.defrag_headroom(..., apply=True)``, which
    itself routes its drains through the arbiter — the controller
    never out-evicts the declared budgets.

    Thread contract: the loop thread reconciles, debug-mux/flight
    readers snapshot the rings — one ``_lock`` over policy state and
    both rings, never held across the scheduler's locks (observe and
    apply run outside it)."""

    def __init__(
        self,
        scheduler,
        policy: Optional[DefragPolicy] = None,
        clock: Callable[[], float] = time.monotonic,
        ring_capacity: int = 256,
        observation_capacity: int = 2048,
    ):
        self.scheduler = scheduler
        self.policy = policy or DefragPolicy()
        self._clock = clock
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=ring_capacity)
        self._obs_ring: deque = deque(maxlen=observation_capacity)
        self._streak = 0
        self._last_decision_now: Optional[float] = None
        self._last_reconcile_at: Optional[float] = None
        self._decisions_total = 0
        self._seq = 0

    # -- observe --------------------------------------------------------------

    def observe(self, now: float) -> dict:
        """One whole-truth observation. Free capacity is requests-based
        (allocatable minus the request vectors of assigned pods — the
        same arithmetic the solver packs against); demand is the
        elementwise-max member request of each pending gang."""
        from koordinator_tpu.apis.extension import NUM_RESOURCES
        from koordinator_tpu.apis.types import resources_to_vector

        snapshot = self.scheduler.cache.snapshot(now=now)
        used: Dict[str, np.ndarray] = {}
        for pod in snapshot.pods:
            if not pod.node_name:
                continue
            vec = resources_to_vector(pod.requests)
            if pod.node_name in used:
                used[pod.node_name] = used[pod.node_name] + vec
            else:
                used[pod.node_name] = vec.copy()
        zeros = np.zeros(NUM_RESOURCES, dtype=np.int64)
        free_rows = []
        for node in snapshot.nodes:
            if node.unschedulable:
                continue
            free_rows.append(
                resources_to_vector(node.allocatable)
                - used.get(node.name, zeros)
            )
        free = (np.stack(free_rows) if free_rows
                else np.zeros((0, NUM_RESOURCES), dtype=np.int64))
        total_free = free.sum(axis=0) if free.size else zeros
        # pending gang demand: per gang, the elementwise-max member
        # request (the hole one member needs) + the min member priority
        # (drains must stay strictly below the preemptor's band)
        demands: Dict[str, np.ndarray] = {}
        floors: Dict[str, int] = {}
        for pod in snapshot.pending_pods:
            if not pod.gang:
                continue
            vec = resources_to_vector(pod.requests)
            if pod.gang in demands:
                demands[pod.gang] = np.maximum(demands[pod.gang], vec)
                floors[pod.gang] = min(floors[pod.gang], pod.priority)
            else:
                demands[pod.gang] = vec
                floors[pod.gang] = pod.priority
        frag_gang = None
        frag_demand = None
        for gang in sorted(demands):
            demand = demands[gang]
            fits_now = bool(
                free.size and (demand[None, :] <= free).all(axis=1).any()
            )
            capacity_exists = bool((demand <= total_free).all())
            if not fits_now and capacity_exists:
                if frag_demand is None or (
                    int(demand.sum()) < int(frag_demand.sum())
                ):
                    frag_gang = gang
                    frag_demand = demand
        obs = {
            "seq": 0,
            "now": float(now),
            "frag": frag_gang is not None,
            "gang": frag_gang,
            "demand": (
                None if frag_demand is None else frag_demand.tolist()
            ),
            "max_victim_priority": (
                None if frag_gang is None else floors[frag_gang]
            ),
            "pending_gangs": len(demands),
            "total_free": total_free.tolist(),
        }
        with self._lock:
            self._seq += 1
            obs["seq"] = self._seq
            self._obs_ring.append(obs)
        return obs

    # -- the pure policy step -------------------------------------------------

    def step(self, obs: dict) -> Optional[dict]:
        with self._lock:
            return self._step_locked(obs)

    def _step_locked(self, obs: dict) -> Optional[dict]:
        # streak bookkeeping EVERY reconcile, decision gates after
        if obs["frag"]:
            self._streak += 1
        else:
            self._streak = 0
            return None
        if self._streak < max(int(self.policy.confirm), 1):
            return None
        now = obs["now"]
        if (
            self._last_decision_now is not None
            and now - self._last_decision_now < self.policy.cooldown_s
        ):
            return None
        self._last_decision_now = now
        self._streak = 0
        self._decisions_total += 1
        decision = {
            "seq": obs["seq"],
            "now": now,
            "signal": "frag-over",
            "gang": obs["gang"],
            "demand": obs["demand"],
            "max_victim_priority": obs["max_victim_priority"],
            "dry_run": self.policy.dry_run,
        }
        self._ring.append(decision)
        return decision

    # -- reconcile ------------------------------------------------------------

    def reconcile(self, now: Optional[float] = None,
                  force: bool = False) -> Optional[dict]:
        if now is None:
            now = self._clock()
        with self._lock:
            if not force and self._last_reconcile_at is not None and (
                now - self._last_reconcile_at < self.policy.interval_s
            ):
                return None
            self._last_reconcile_at = now
        obs = self.observe(now)
        decision = self.step(obs)
        if decision is None:
            return None
        DEFRAG_DECISIONS.inc({"signal": "frag-over"})
        if not self.policy.dry_run:
            got = self.scheduler.defrag_headroom(
                np.asarray(decision["demand"], dtype=np.int64),
                decision["max_victim_priority"],
                apply=True,
                now=now,
            )
            outcome = {
                "node": None if got is None else got[0],
                "drains": [] if got is None else list(got[1]),
            }
        else:
            outcome = {"node": None, "drains": [], "skipped": "dry-run"}
        with self._lock:
            decision["outcome"] = outcome
        return decision

    def maybe_reconcile(self, now: Optional[float] = None):
        return self.reconcile(now=now, force=False)

    # -- observability --------------------------------------------------------

    def decisions_total(self) -> int:
        with self._lock:
            return self._decisions_total

    def status(self) -> dict:
        with self._lock:
            return {
                "policy": dataclasses.asdict(self.policy),
                "streak": self._streak,
                "decisions_total": self._decisions_total,
                "last_decision_now": self._last_decision_now,
                "decisions": list(self._ring)[-16:],
                "observations": len(self._obs_ring),
            }

    def flight_payload(self) -> dict:
        with self._lock:
            return {
                "decisions": list(self._ring)[-16:],
                "observations": list(self._obs_ring)[-16:],
            }

    def replay_decisions(self) -> List[dict]:
        """Re-drive a FRESH policy over the recorded observation ring
        (the §25 replay contract): the re-derived decision stream must
        equal the recorded ring bit-for-bit (modulo the post-hoc
        ``outcome`` annotation, which is the applied world's answer,
        not the policy's)."""
        with self._lock:
            observations = list(self._obs_ring)
        fresh = DefragController(
            scheduler=None, policy=self.policy, clock=lambda: 0.0,
        )
        out: List[dict] = []
        for obs in observations:
            d = fresh._step_locked(dict(obs))
            if d is not None:
                out.append(d)
        return out
