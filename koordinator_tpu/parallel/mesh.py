"""Mesh construction + node/pod-batch sharding for the batched solver.

Layout: a ``nodes × pods`` 2-D mesh (DESIGN.md §19). The two axes shard
the two independent scale dimensions of the workload:

- ``nodes`` — every ``[N, ...]`` node-side array splits on its leading
  axis. Under ``jax.jit`` with these shardings, GSPMD partitions the
  per-pod Filter/Score math over node shards and inserts the cross-chip
  argmax (an ``allreduce-max`` + index select) on ICI — no hand-written
  collectives. This is the CAPACITY axis: it buys node count (the 50k+
  node worlds of bench leg 14) at the price of one tiny per-pod-step
  merge collective.
- ``pods`` — stacked INDEPENDENT pod batches (the admission gate's
  vmap lanes: separate callers' bursts against one shared base) split
  on their leading lane axis. Lanes never interact, so this axis is
  collective-free and scales throughput near-linearly (bench leg 15) —
  the right home for giant pod bursts.

The classic 1-D ``make_mesh`` remains the node-only special case; every
sharding helper below works on either mesh (a ``PartitionSpec`` naming
only one axis replicates over the other).
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from koordinator_tpu.obs.device import DEVICE_OBS
from koordinator_tpu.ops.binpack import (
    NodeState,
    PodBatch,
    ScoreParams,
    SolverConfig,
    schedule_batch,
    solve_batch,
)
from koordinator_tpu.state.cluster import NodeArrays, pad_node_rows

NODE_AXIS = "nodes"
POD_AXIS = "pods"


def _shard_map(fn, *, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` across jax versions: newer releases promote it
    to the top level (``check_vma``); older ones only ship
    ``jax.experimental.shard_map`` (``check_rep``)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def distributed_kernel_supported() -> bool:
    """Whether THIS jax build can run the distributed pallas kernel:
    real remote DMAs need ``pltpu.CompilerParams`` (collective_id +
    side effects), and the off-TPU path additionally needs the TPU
    interpreter's emulated remote DMAs (``pltpu.InterpretParams``).
    Older jax (e.g. 0.4.x) ships neither — callers must fall back to
    the GSPMD scan path (``shard_solver``/``shard_full_solver``), which
    carries the same bit-identity contract without in-kernel
    collectives."""
    try:
        from jax.experimental.pallas import tpu as pltpu
    except ImportError:
        return False

    if not hasattr(pltpu, "CompilerParams"):
        return False
    if jax.devices()[0].platform == "tpu":
        return True
    return hasattr(pltpu, "InterpretParams")


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over all (or the given) devices, axis ``nodes``."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def make_mesh2d(
    devices: Optional[Sequence[jax.Device]] = None,
    node_shards: Optional[int] = None,
    pod_shards: int = 1,
) -> Mesh:
    """The ``nodes × pods`` 2-D mesh: ``node_shards`` splits the node
    axis (capacity), ``pod_shards`` splits the stacked-lane axis of
    independent pod batches (throughput). Defaults: all pod-axis-free
    devices go to the node axis. ``make_mesh2d(pod_shards=k)`` with
    ``node_shards=1`` is the pure burst-sharding mesh of bench leg 15;
    ``make_mesh2d(node_shards=k)`` is the capacity mesh of leg 14."""
    devices = list(devices) if devices is not None else jax.devices()
    if node_shards is None:
        node_shards = max(1, len(devices) // pod_shards)
    want = node_shards * pod_shards
    if want > len(devices):
        raise ValueError(
            f"mesh {node_shards}x{pod_shards} needs {want} devices, "
            f"have {len(devices)}"
        )
    grid = np.asarray(devices[:want]).reshape(node_shards, pod_shards)
    return Mesh(grid, (NODE_AXIS, POD_AXIS))


def mesh_axis_size(mesh: Mesh, axis: str) -> int:
    """Shard count of ``axis`` on ``mesh`` (1 when the mesh lacks it)."""
    return int(mesh.shape.get(axis, 1))


def node_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for node-major arrays: leading axis split over ``nodes``
    (replicated over any other mesh axis)."""
    return NamedSharding(mesh, P(NODE_AXIS))


def lane_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for lane-stacked pod batches: leading (lane) axis split
    over ``pods`` (replicated over the node axis)."""
    return NamedSharding(mesh, P(POD_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def node_shard_count(sharding) -> int:
    """How many ways ``sharding`` splits a node-major array's LEADING
    axis — 1 for None, replicated, or non-Named shardings. The staging
    layer uses this to size the pow2-bucket node padding
    (:func:`shard_node_bucket`) before a mesh ``device_put``."""
    if not isinstance(sharding, NamedSharding):
        return 1
    spec = tuple(sharding.spec)
    if not spec or spec[0] is None:
        return 1
    axes = spec[0] if isinstance(spec[0], tuple) else (spec[0],)
    count = 1
    for axis in axes:
        count *= mesh_axis_size(sharding.mesh, axis)
    return count


def pow2_quarter_bucket(n: int, floor: int = 8) -> int:
    """THE shape-bucket family of the repo: round ``n`` up to a quarter
    step between powers of two (floor ``floor``). Shared by the staged
    pod batches (``PlacementModel.pod_bucket``), the per-shard node
    widths below, and the multi-tenant pool's node/pod/lane buckets
    (service/tenancy.py) — one family, so "nearby sizes reuse one
    compiled program at <= ~12.5% padding waste" means the same thing
    on every axis."""
    if n <= floor:
        return floor
    power = 1 << (n - 1).bit_length()
    step = max(1, power // 8)
    return ((n + step - 1) // step) * step


def shard_node_bucket(n: int, shards: int) -> int:
    """The padded GLOBAL node count for ``n`` real nodes over
    ``shards`` shards: each shard's local width is the quarter-step
    pow2 bucket of ``ceil(n / shards)`` (floor 8) — the same bucketing
    family as ``StagedStateCache``'s pod/dirty-row buckets, so a
    drifting node count re-uses one compiled sharded program per bucket
    while bounding padding waste at ~12.5% (plus the divisibility
    remainder). Every shard is equal-width, so a ``NamedSharding``
    ``device_put`` never needs uneven layouts."""
    if shards <= 1:
        return n
    return pow2_quarter_bucket(-(-n // shards)) * shards


def shard_tile_bucket(n: int, shards: int) -> int:
    """The padded GLOBAL node count for the sharded pallas kernel:
    each shard's local width is ``ceil(n / shards)`` tile-aligned to
    the 128-lane VPU register width, every shard equal-width. The
    kernel-ABI sibling of :func:`shard_node_bucket` — a named member
    of the repo bucket family (docs/DESIGN.md §23) so graftcheck's
    shape-flow passes can enumerate its finite image. The math is the
    inline form PR 12 shipped, bit for bit."""
    local = ((n + 128 * shards - 1) // (128 * shards)) * 128
    return local * shards


def pad_node_arrays(arrays: NodeArrays, multiple: int) -> NodeArrays:
    """Pad the node axis up to a multiple of the shard count.

    Padding nodes are unschedulable with zero allocatable, so they can
    never win a placement — semantics are unchanged. Row construction
    lives in :func:`state.cluster.pad_node_rows` (the delta-parity
    registry) so a padded row can never drift from "a permanently
    empty node"."""
    target = ((arrays.n + multiple - 1) // multiple) * multiple
    return pad_node_rows(arrays, target)


def shard_node_state(state: NodeState, mesh: Mesh) -> NodeState:
    """Device-put a ``NodeState`` with the node axis sharded over the mesh."""
    ns = node_sharding(mesh)
    return NodeState(*(jax.device_put(x, ns) for x in state))


def shard_solver(mesh: Mesh, config: SolverConfig = SolverConfig()):
    """Jitted solver with explicit shardings over the mesh.

    Returns ``solve(state, pods, params) -> (state', assignments)`` where
    ``state`` is node-sharded and ``pods``/``params`` replicated. The
    assignments come back replicated (each chip learns every argmax winner
    through the reduction); the updated node state stays sharded for the
    next churn batch — state lives on device across solves.
    """
    ns = node_sharding(mesh)
    rep = replicated(mesh)
    state_sh = NodeState(*([ns] * len(NodeState._fields)))
    pods_sh = PodBatch(*([rep] * len(PodBatch._fields)))
    params_sh = ScoreParams(*([rep] * len(ScoreParams._fields)))
    return DEVICE_OBS.jit("shard_solver", jax.jit(
        partial(schedule_batch, config=config),
        in_shardings=(state_sh, pods_sh, params_sh),
        out_shardings=(state_sh, rep),
        static_argnums=(), donate_argnums=(),
    ))


def shard_kernel_solver(mesh: Mesh, config: SolverConfig = SolverConfig(),
                        interpret: Optional[bool] = None):
    """The pallas kernel composed under ``jax.shard_map`` (VERDICT r4
    #3): each device keeps its node shard's carry in VMEM and the
    kernels merge every pod's winner across shards with an in-kernel
    all-to-all of the packed (score, global node) best over remote DMAs
    — multi-chip inherits kernel throughput instead of dropping to the
    HBM-streaming scan.

    Returns ``solve(state, pods, params, quota_state=None,
    gang_state=None, numa_aux=None) -> SolveResult`` with bit-identical
    outputs to single-device ``solve_batch``/``pallas_solve_batch``
    (smallest-node-index tie-breaks included — the packed exchange
    carries global lane ids). Node-count padding: the node axis is
    padded with unschedulable zero rows to shards x 128 lanes before
    sharding; assignments are remapped back to original indices.

    On CPU (tests / the driver dryrun) the kernels run under the TPU
    interpreter with emulated remote DMAs — the same program, same
    synchronization, slower clock.
    """
    if not distributed_kernel_supported():
        raise RuntimeError(
            "distributed pallas kernel unavailable on this jax build "
            "(needs pltpu.CompilerParams, and pltpu.InterpretParams "
            "off-TPU) — use shard_solver/shard_full_solver (GSPMD scan)"
        )
    from koordinator_tpu.ops.pallas_binpack import (
        _kernel_epilogue,
        _pallas_solve,
    )
    from koordinator_tpu.ops.quota import quota_runtime

    devices = list(mesh.devices.flat)
    # the in-kernel merge is a NODE-axis collective: on a 2-D mesh the
    # remote-DMA ring spans exactly the node axis. A pod-sharded lane
    # axis would need per-lane rings the kernel does not build — route
    # lane bursts through shard_lane_solver instead.
    if mesh_axis_size(mesh, POD_AXIS) > 1:
        raise ValueError(
            "shard_kernel_solver shards the node axis only — use "
            "shard_lane_solver for a pod-batch-sharded mesh"
        )
    k = (
        mesh_axis_size(mesh, NODE_AXIS)
        if NODE_AXIS in mesh.shape else len(devices)
    )

    def solve(state, pods, params, quota_state=None, gang_state=None,
              numa_aux=None, resv=None):
        import jax.numpy as jnp

        from koordinator_tpu.ops.pallas_binpack import (
            pallas_resv_supported,
            pallas_supported,
        )

        if not pallas_supported(params, config):
            # same guard as the single-chip kernel dispatch: scoring
            # modes the kernel does not implement must raise, not
            # silently diverge
            raise ValueError(
                "configuration not supported by the pallas kernel"
            )
        nonlocal_interpret = interpret
        if nonlocal_interpret is None:
            nonlocal_interpret = devices[0].platform != "tpu"
        use_q = quota_state is not None
        use_n = numa_aux is not None
        wsum = int(np.asarray(params.weights).sum()) or 1
        n = state.alloc.shape[0]
        # pad the node axis to shards x 128-lane multiples with
        # unschedulable zero rows (they can never win)
        n_pad = shard_tile_bucket(n, k)
        n_loc = n_pad // k
        if n_pad > 65536:
            raise ValueError("packed argmax carries 16 lane bits")
        use_r = resv is not None
        if use_r and not pallas_resv_supported(resv.node.shape[0], n_loc):
            raise ValueError(
                "reservation table unsupported by the sharded kernel "
                "(empty table: pass resv=None; otherwise too large) — "
                "use the sharded scan"
            )
        if use_r:
            from koordinator_tpu.ops.pallas_binpack import (
                pallas_resv_score_safe,
            )

            if not pallas_resv_score_safe(resv.node, resv.free,
                                          state.alloc):
                raise ValueError(
                    "reservation credit could overflow the packed "
                    "argmax's score budget — use the sharded scan"
                )

        def padn(a, fill=0):
            if a is None:
                return None
            pw = [(0, n_pad - n)] + [(0, 0)] * (a.ndim - 1)
            return jnp.pad(a, pw, constant_values=fill)

        state = NodeState(
            alloc=padn(state.alloc),
            used_req=padn(state.used_req),
            usage=padn(state.usage),
            prod_usage=padn(state.prod_usage),
            est_extra=padn(state.est_extra),
            prod_base=padn(state.prod_base),
            metric_fresh=padn(state.metric_fresh),
            schedulable=padn(state.schedulable),
            numa_cap=padn(state.numa_cap),
            numa_free=padn(state.numa_free),
        )
        npol = padn(numa_aux.node_policy) if use_n else None
        quota_in = None
        if use_q:
            runtime = quota_runtime(quota_state)
            quota_in = (quota_state.min, runtime, quota_state.used,
                        quota_state.np_used)
        # reservation tables are tiny [V,R]; they replicate, every shard
        # replays the same global consumption trajectory (the merged
        # winner is global), and the one-hot's lanes get the shard
        # offset inside _pallas_solve
        resv_in = (
            (resv.node, resv.free, resv.allocate_once, resv.match)
            if use_r else None
        )

        ns_spec = P(NODE_AXIS)
        rep = P()
        state_specs = NodeState(
            alloc=ns_spec, used_req=ns_spec, usage=ns_spec,
            prod_usage=ns_spec, est_extra=ns_spec, prod_base=ns_spec,
            metric_fresh=ns_spec, schedulable=ns_spec,
            numa_cap=ns_spec if use_n else None,
            numa_free=ns_spec if use_n else None,
        )
        pods_specs = jax.tree.map(lambda _: rep, pods)
        quota_specs = (rep, rep, rep, rep) if use_q else None

        def body(state_l, pods_l, params_l, quota_l, npol_l, resv_l):
            numa_in = None
            if use_n:
                numa_in = (state_l.numa_cap, state_l.numa_free, npol_l)
            new_state, assign, qused, qnp, consumed, resv_out = (
                _pallas_solve(
                    state_l, pods_l, params_l, wsum, nonlocal_interpret,
                    quota_l, numa_in, bool(config.numa_most_allocated),
                    n_shards=k, axis_name=NODE_AXIS, resv=resv_l,
                )
            )
            if consumed is None:
                consumed = jnp.zeros(assign.shape[0], bool)
            return new_state, assign, qused, qnp, consumed[None, :], resv_out

        body_sharded = _shard_map(
            body, mesh=mesh,
            in_specs=(state_specs, pods_specs,
                      jax.tree.map(lambda _: rep, params),
                      quota_specs, ns_spec if use_n else None,
                      (rep, rep, rep, rep) if use_r else None),
            out_specs=(state_specs, rep,
                       rep if use_q else None,
                       rep if use_q else None,
                       P(NODE_AXIS, None),
                       (rep, rep, rep, rep) if use_r else None),
            check_vma=False,
        )

        @partial(jax.jit, static_argnums=(), donate_argnums=())
        def run(state, pods, params, quota_in, npol, resv_in, quota_state,
                gang_state):
            new_state, assign, qused, qnp, consumed_k, resv_out = (
                body_sharded(state, pods, params, quota_in, npol, resv_in)
            )
            # the node axis was padded GLOBALLY (then sharded), and each
            # shard's width is already a 128-lane multiple, so the
            # kernel's global packed lane IS the original node index —
            # no remap needed, and tie-breaks match single-device
            consumed = consumed_k.any(axis=0) if use_n else None
            final_qstate = (
                quota_state._replace(used=qused, np_used=qnp)
                if use_q else None
            )
            result = _kernel_epilogue(
                new_state, assign, consumed, final_qstate, pods,
                gang_state, gang_state is not None, use_n,
                resv_out=resv_out,
            )
            return result

        result = run(state, pods, params, quota_in, npol, resv_in,
                     quota_state, gang_state)
        # strip node padding back off
        trim = lambda a: None if a is None else a[:n]
        return result._replace(
            node_state=NodeState(*(trim(x) for x in result.node_state))
        )

    return solve


def stack_pod_lanes(batches: Sequence[PodBatch]) -> PodBatch:
    """Stack K independent same-shape pod batches into one ``[K, P,
    ...]`` lane batch for :func:`shard_lane_solver`. Lanes must agree on
    pod count and on whether ``has_numa_policy`` is carried (the stack
    is a shape operation, not a semantic merge — every lane still
    solves alone against the shared base, exactly like the admission
    gate's coalesced vmap stack)."""
    import jax.numpy as jnp

    if not batches:
        raise ValueError("stack_pod_lanes needs at least one batch")
    cols = []
    for field in range(len(PodBatch._fields)):
        vals = [b[field] for b in batches]
        if all(v is None for v in vals):
            cols.append(None)
        elif any(v is None for v in vals):
            raise ValueError(
                f"lanes disagree on PodBatch.{PodBatch._fields[field]} "
                "presence — stack only uniform batches"
            )
        else:
            cols.append(jnp.stack(vals))
    return PodBatch(*cols)


def shard_lane_solver(mesh: Mesh, config: SolverConfig = SolverConfig(),
                      want_state: bool = True):
    """The pod-batch axis of the 2-D mesh: K INDEPENDENT lanes (stacked
    pod batches over one shared node base — the admission gate's
    coalesce shape, or any giant burst split into independent waves)
    solved as one vmapped program with the lane axis sharded over
    ``pods``.

    Returns ``solve(state, lanes, params) -> (node_states, assign)``
    where ``lanes`` is a ``[L, P, ...]`` :class:`PodBatch` (build with
    :func:`stack_pod_lanes`), ``node_states`` is the per-lane mutated
    carry ``[L, N, ...]`` and ``assign`` is ``[L, P]``. Lanes never
    communicate — no per-step collective exists on this axis, so
    wall-clock scales with the shard count (bench leg 15) — and each
    lane is bit-identical to solving it alone (the int-arithmetic vmap
    property the admission gate already leans on). The node axis of the
    base follows the mesh's ``nodes`` axis when it is >1 (a true 2-D
    run); on a lane-only mesh the base replicates.

    The lane count is padded up to a shard multiple with hard-blocked
    duplicate lanes (placements discarded, outputs trimmed) so any L
    works; the waste rides the ``pod_lanes`` padding gauge.

    ``want_state=False`` compiles an assignments-only program
    (``node_states`` comes back None): callers that only read
    placements skip materializing the ``[L, N, ...]`` per-lane carries
    — at 32 lanes x thousands of nodes those outputs are tens of MB a
    call and (measured on the virtual-CPU mesh) their allocator churn
    is the difference between a clean scaling curve and a noisy one."""
    import jax.numpy as jnp

    ns = node_sharding(mesh)
    lane = lane_sharding(mesh)
    rep = replicated(mesh)
    k = mesh_axis_size(mesh, POD_AXIS)

    if want_state:
        body = lambda s, p, pr: (
            lambda r: (r.node_state, r.assign)
        )(solve_batch(s, p, pr, config))
    else:
        body = lambda s, p, pr: (
            None, solve_batch(s, p, pr, config).assign
        )
    jit_lanes = DEVICE_OBS.jit("shard_lane_solver", jax.jit(
        jax.vmap(body, in_axes=(None, 0, None)),
        static_argnums=(), donate_argnums=(),
    ))

    def pad_lanes(lanes: PodBatch, pad: int) -> PodBatch:
        def dup(a):
            if a is None:
                return None
            return jnp.concatenate([a, jnp.repeat(a[-1:], pad, axis=0)])

        padded = PodBatch(*(dup(x) for x in lanes))
        # padding lanes are copies of the last real lane with every pod
        # hard-blocked: they place nothing, mutate nothing that
        # survives the trim, and keep every shard equal-width
        return padded._replace(
            blocked=padded.blocked.at[-pad:].set(True)
        )

    def solve(state: NodeState, lanes: PodBatch, params: ScoreParams):
        l_real = int(lanes.req.shape[0])
        target = -(-l_real // k) * k
        DEVICE_OBS.note_padding("pod_lanes", l_real, target)
        if target != l_real:
            lanes = pad_lanes(lanes, target - l_real)
        state = jax.device_put(state, jax.tree.map(lambda _: ns, state))
        lanes = jax.device_put(lanes, jax.tree.map(lambda _: lane, lanes))
        params = jax.device_put(params, jax.tree.map(lambda _: rep, params))
        node_states, assign = jit_lanes(state, lanes, params)
        if target != l_real:
            if node_states is not None:
                node_states = NodeState(*(
                    None if x is None else x[:l_real] for x in node_states
                ))
            assign = assign[:l_real]
        return node_states, assign

    return solve


def stack_node_states(states: Sequence[NodeState]) -> NodeState:
    """Stack K same-shape node worlds into one ``[K, N, ...]`` base
    stack for :func:`shard_tenant_solver` — the multi-tenant twin of
    :func:`stack_pod_lanes`. Worlds must agree on shape (the tenancy
    layer pads every world to one node bucket first) and on optional
    column presence; like the lane stack this is a shape operation,
    never a semantic merge: lane k still solves against exactly its own
    world."""
    import jax.numpy as jnp

    if not states:
        raise ValueError("stack_node_states needs at least one world")
    cols = []
    for field in range(len(NodeState._fields)):
        vals = [s[field] for s in states]
        if all(v is None for v in vals):
            cols.append(None)
        elif any(v is None for v in vals):
            raise ValueError(
                f"worlds disagree on NodeState.{NodeState._fields[field]} "
                "presence — stack only uniform worlds"
            )
        else:
            cols.append(jnp.stack(vals))
    return NodeState(*cols)


def shard_tenant_solver(mesh: Mesh, config: SolverConfig = SolverConfig(),
                        want_state: bool = False):
    """The multi-tenant generalization of :func:`shard_lane_solver`:
    K INDEPENDENT lanes where every lane carries its OWN node base and
    its OWN params — K tenants' per-tick solves batched as one vmapped
    program with the lane axis sharded over ``pods``.

    Returns ``solve(states, lanes, params) -> (used_req, assign)``
    where ``states`` is a ``[L, N, ...]`` :class:`NodeState` stack
    (build with :func:`stack_node_states`), ``lanes`` a ``[L, P, ...]``
    :class:`PodBatch` stack and ``params`` a ``[L, ...]``
    :class:`ScoreParams` stack; ``assign`` is ``[L, P]`` and
    ``used_req`` the per-lane mutated ``[L, N, R]`` accounting (None
    under the default ``want_state=False`` — the multi-tenant gate path
    reads placements only, and PR 15 measured the state carry's
    allocator churn at 3-10x timing noise for small L).

    Tenants never communicate — same collective-free scaling as the
    single-base lane axis — and each lane is bit-identical to that
    tenant solving alone (the int-arithmetic vmap property), which is
    what makes the multi-tenant pool's isolation contract testable.
    Lane-count padding mirrors :func:`shard_lane_solver`: duplicate
    hard-blocked lanes up to a shard multiple, outputs trimmed."""
    import jax.numpy as jnp

    lane = lane_sharding(mesh)
    k = mesh_axis_size(mesh, POD_AXIS)

    if want_state:
        body = lambda s, p, pr: (
            lambda r: (r.node_state.used_req, r.assign)
        )(solve_batch(s, p, pr, config))
    else:
        body = lambda s, p, pr: (
            None, solve_batch(s, p, pr, config).assign
        )
    jit_lanes = DEVICE_OBS.jit("shard_tenant_solver", jax.jit(
        jax.vmap(body, in_axes=(0, 0, 0)),
        static_argnums=(), donate_argnums=(),
    ))

    def dup_pad(tree, pad):
        def dup(a):
            if a is None:
                return None
            return jnp.concatenate([a, jnp.repeat(a[-1:], pad, axis=0)])

        return type(tree)(*(dup(x) for x in tree))

    def solve(states: NodeState, lanes: PodBatch, params: ScoreParams):
        l_real = int(lanes.req.shape[0])
        target = -(-l_real // k) * k
        DEVICE_OBS.note_padding("tenant_lanes", l_real, target)
        if target != l_real:
            pad = target - l_real
            states = dup_pad(states, pad)
            params = dup_pad(params, pad)
            lanes = dup_pad(lanes, pad)
            # padding lanes are copies of the last real lane with every
            # pod hard-blocked: they place nothing and are trimmed off
            lanes = lanes._replace(
                blocked=lanes.blocked.at[-pad:].set(True)
            )
        put = lambda tree: jax.device_put(
            tree, jax.tree.map(lambda _: lane, tree)
        )
        used_req, assign = jit_lanes(put(states), put(lanes), put(params))
        if target != l_real:
            assign = assign[:l_real]
            if used_req is not None:
                used_req = used_req[:l_real]
        return used_req, assign

    return solve


def shard_full_solver(mesh: Mesh, config: SolverConfig = SolverConfig()):
    """Jitted FULL solve (quota admission, gang resolution, NUMA) with
    the node axis sharded — the multi-chip counterpart of
    ``ops.binpack.solve_batch(state, pods, params, config, quota_state,
    gang_state, numa=numa_aux)``.

    Node-major arrays (NodeState incl. numa inventories, NumaAux's
    node_policy) shard over ``nodes``; pod batches, quota and gang state
    replicate — quota groups and gangs are small [Q,R]/[G] tables every
    chip can hold, while the [N,R] node axis is the scaling dimension.
    GSPMD inserts the cross-shard argmax and the segment reductions of
    the gang epilogue. Optional features are trace-time static: pass
    None to drop a subsystem (a separate program per combination, as in
    the single-chip path).
    """
    from koordinator_tpu.ops.binpack import NumaAux, solve_batch

    ns = node_sharding(mesh)
    rep = replicated(mesh)
    jit_full = DEVICE_OBS.jit("shard_full_solver", jax.jit(
        lambda s, p, pr, q, g, x, r, n: solve_batch(
            s, p, pr, config, q, g, extras=x, resv=r, numa=n
        ),
        static_argnums=(), donate_argnums=(),
    ))

    def solve(state, pods, params, quota_state=None, gang_state=None,
              numa_aux=None, extras=None, resv=None):
        state = shard_node_state(state, mesh)
        pods = jax.device_put(pods, rep)
        params = jax.device_put(params, rep)
        if quota_state is not None:
            quota_state = jax.device_put(quota_state, rep)
        if gang_state is not None:
            gang_state = jax.device_put(gang_state, rep)
        if extras is not None:
            extras = jax.device_put(extras, rep)
        if resv is not None:
            # reservation tables are tiny [V,R]; replicate them and let
            # GSPMD gather/scatter the per-node credit against the
            # sharded used_req
            resv = jax.device_put(resv, rep)
        if numa_aux is not None:
            numa_aux = NumaAux(
                node_policy=jax.device_put(numa_aux.node_policy, ns)
            )
        return jit_full(state, pods, params, quota_state, gang_state,
                        extras, resv, numa_aux)

    return solve
