"""Mesh construction + node-axis sharding for the batched solver.

Layout: a 1-D mesh over all available chips, axis ``nodes``. Every
``[N, ...]`` node-side array is sharded on its leading axis; pod batches
and scoring parameters are replicated. Under ``jax.jit`` with these
shardings, GSPMD partitions the per-pod Filter/Score math over node shards
and inserts the cross-chip argmax (an ``allreduce-max`` + index select)
on ICI — no hand-written collectives needed.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from koordinator_tpu.ops.binpack import (
    NodeState,
    PodBatch,
    ScoreParams,
    SolverConfig,
    schedule_batch,
)
from koordinator_tpu.state.cluster import NodeArrays

NODE_AXIS = "nodes"


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over all (or the given) devices, axis ``nodes``."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def node_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for node-major arrays: leading axis split over ``nodes``."""
    return NamedSharding(mesh, P(NODE_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_node_arrays(arrays: NodeArrays, multiple: int) -> NodeArrays:
    """Pad the node axis up to a multiple of the shard count.

    Padding nodes are unschedulable with zero allocatable, so they can
    never win a placement — semantics are unchanged.
    """
    n = arrays.n
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return arrays
    pad = target - n

    def pad2d(a):
        return np.pad(a, ((0, pad), (0, 0)))

    return dataclasses.replace(
        arrays,
        names=arrays.names + [f"__pad_{i}__" for i in range(pad)],
        alloc=pad2d(arrays.alloc),
        used_req=pad2d(arrays.used_req),
        usage=pad2d(arrays.usage),
        prod_usage=pad2d(arrays.prod_usage),
        est_extra=pad2d(arrays.est_extra),
        prod_base=pad2d(arrays.prod_base),
        metric_fresh=np.pad(arrays.metric_fresh, (0, pad)),
        schedulable=np.pad(arrays.schedulable, (0, pad)),  # False padding
    )


def shard_node_state(state: NodeState, mesh: Mesh) -> NodeState:
    """Device-put a ``NodeState`` with the node axis sharded over the mesh."""
    ns = node_sharding(mesh)
    return NodeState(*(jax.device_put(x, ns) for x in state))


def shard_solver(mesh: Mesh, config: SolverConfig = SolverConfig()):
    """Jitted solver with explicit shardings over the mesh.

    Returns ``solve(state, pods, params) -> (state', assignments)`` where
    ``state`` is node-sharded and ``pods``/``params`` replicated. The
    assignments come back replicated (each chip learns every argmax winner
    through the reduction); the updated node state stays sharded for the
    next churn batch — state lives on device across solves.
    """
    ns = node_sharding(mesh)
    rep = replicated(mesh)
    state_sh = NodeState(*([ns] * len(NodeState._fields)))
    pods_sh = PodBatch(*([rep] * len(PodBatch._fields)))
    params_sh = ScoreParams(*([rep] * len(ScoreParams._fields)))
    return jax.jit(
        partial(schedule_batch, config=config),
        in_shardings=(state_sh, pods_sh, params_sh),
        out_shardings=(state_sh, rep),
    )


def shard_full_solver(mesh: Mesh, config: SolverConfig = SolverConfig()):
    """Jitted FULL solve (quota admission, gang resolution, NUMA) with
    the node axis sharded — the multi-chip counterpart of
    ``ops.binpack.solve_batch(state, pods, params, config, quota_state,
    gang_state, numa=numa_aux)``.

    Node-major arrays (NodeState incl. numa inventories, NumaAux's
    node_policy) shard over ``nodes``; pod batches, quota and gang state
    replicate — quota groups and gangs are small [Q,R]/[G] tables every
    chip can hold, while the [N,R] node axis is the scaling dimension.
    GSPMD inserts the cross-shard argmax and the segment reductions of
    the gang epilogue. Optional features are trace-time static: pass
    None to drop a subsystem (a separate program per combination, as in
    the single-chip path).
    """
    from koordinator_tpu.ops.binpack import NumaAux, solve_batch

    ns = node_sharding(mesh)
    rep = replicated(mesh)
    jit_full = jax.jit(
        lambda s, p, pr, q, g, n: solve_batch(s, p, pr, config, q, g, numa=n)
    )

    def solve(state, pods, params, quota_state=None, gang_state=None,
              numa_aux=None):
        state = shard_node_state(state, mesh)
        pods = jax.device_put(pods, rep)
        params = jax.device_put(params, rep)
        if quota_state is not None:
            quota_state = jax.device_put(quota_state, rep)
        if gang_state is not None:
            gang_state = jax.device_put(gang_state, rep)
        if numa_aux is not None:
            numa_aux = NumaAux(
                node_policy=jax.device_put(numa_aux.node_policy, ns)
            )
        return jit_full(state, pods, params, quota_state, gang_state, numa_aux)

    return solve
