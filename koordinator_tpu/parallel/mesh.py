"""Mesh construction + node-axis sharding for the batched solver.

Layout: a 1-D mesh over all available chips, axis ``nodes``. Every
``[N, ...]`` node-side array is sharded on its leading axis; pod batches
and scoring parameters are replicated. Under ``jax.jit`` with these
shardings, GSPMD partitions the per-pod Filter/Score math over node shards
and inserts the cross-chip argmax (an ``allreduce-max`` + index select)
on ICI — no hand-written collectives needed.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from koordinator_tpu.obs.device import DEVICE_OBS
from koordinator_tpu.ops.binpack import (
    NodeState,
    PodBatch,
    ScoreParams,
    SolverConfig,
    schedule_batch,
)
from koordinator_tpu.state.cluster import NodeArrays

NODE_AXIS = "nodes"


def _shard_map(fn, *, mesh, in_specs, out_specs, check_vma=False):
    """``jax.shard_map`` across jax versions: newer releases promote it
    to the top level (``check_vma``); older ones only ship
    ``jax.experimental.shard_map`` (``check_rep``)."""
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map

    return shard_map(
        fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )


def distributed_kernel_supported() -> bool:
    """Whether THIS jax build can run the distributed pallas kernel:
    real remote DMAs need ``pltpu.CompilerParams`` (collective_id +
    side effects), and the off-TPU path additionally needs the TPU
    interpreter's emulated remote DMAs (``pltpu.InterpretParams``).
    Older jax (e.g. 0.4.x) ships neither — callers must fall back to
    the GSPMD scan path (``shard_solver``/``shard_full_solver``), which
    carries the same bit-identity contract without in-kernel
    collectives."""
    try:
        from jax.experimental.pallas import tpu as pltpu
    except ImportError:
        return False

    if not hasattr(pltpu, "CompilerParams"):
        return False
    if jax.devices()[0].platform == "tpu":
        return True
    return hasattr(pltpu, "InterpretParams")


def make_mesh(devices: Optional[Sequence[jax.Device]] = None) -> Mesh:
    """1-D mesh over all (or the given) devices, axis ``nodes``."""
    devices = list(devices) if devices is not None else jax.devices()
    return Mesh(np.asarray(devices), (NODE_AXIS,))


def node_sharding(mesh: Mesh) -> NamedSharding:
    """Sharding for node-major arrays: leading axis split over ``nodes``."""
    return NamedSharding(mesh, P(NODE_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    return NamedSharding(mesh, P())


def pad_node_arrays(arrays: NodeArrays, multiple: int) -> NodeArrays:
    """Pad the node axis up to a multiple of the shard count.

    Padding nodes are unschedulable with zero allocatable, so they can
    never win a placement — semantics are unchanged.
    """
    n = arrays.n
    target = ((n + multiple - 1) // multiple) * multiple
    if target == n:
        return arrays
    pad = target - n

    def pad2d(a):
        return np.pad(a, ((0, pad), (0, 0)))

    return dataclasses.replace(
        arrays,
        names=arrays.names + [f"__pad_{i}__" for i in range(pad)],
        alloc=pad2d(arrays.alloc),
        used_req=pad2d(arrays.used_req),
        usage=pad2d(arrays.usage),
        prod_usage=pad2d(arrays.prod_usage),
        est_extra=pad2d(arrays.est_extra),
        prod_base=pad2d(arrays.prod_base),
        metric_fresh=np.pad(arrays.metric_fresh, (0, pad)),
        schedulable=np.pad(arrays.schedulable, (0, pad)),  # False padding
    )


def shard_node_state(state: NodeState, mesh: Mesh) -> NodeState:
    """Device-put a ``NodeState`` with the node axis sharded over the mesh."""
    ns = node_sharding(mesh)
    return NodeState(*(jax.device_put(x, ns) for x in state))


def shard_solver(mesh: Mesh, config: SolverConfig = SolverConfig()):
    """Jitted solver with explicit shardings over the mesh.

    Returns ``solve(state, pods, params) -> (state', assignments)`` where
    ``state`` is node-sharded and ``pods``/``params`` replicated. The
    assignments come back replicated (each chip learns every argmax winner
    through the reduction); the updated node state stays sharded for the
    next churn batch — state lives on device across solves.
    """
    ns = node_sharding(mesh)
    rep = replicated(mesh)
    state_sh = NodeState(*([ns] * len(NodeState._fields)))
    pods_sh = PodBatch(*([rep] * len(PodBatch._fields)))
    params_sh = ScoreParams(*([rep] * len(ScoreParams._fields)))
    return DEVICE_OBS.jit("shard_solver", jax.jit(
        partial(schedule_batch, config=config),
        in_shardings=(state_sh, pods_sh, params_sh),
        out_shardings=(state_sh, rep),
        static_argnums=(), donate_argnums=(),
    ))


def shard_kernel_solver(mesh: Mesh, config: SolverConfig = SolverConfig(),
                        interpret: Optional[bool] = None):
    """The pallas kernel composed under ``jax.shard_map`` (VERDICT r4
    #3): each device keeps its node shard's carry in VMEM and the
    kernels merge every pod's winner across shards with an in-kernel
    all-to-all of the packed (score, global node) best over remote DMAs
    — multi-chip inherits kernel throughput instead of dropping to the
    HBM-streaming scan.

    Returns ``solve(state, pods, params, quota_state=None,
    gang_state=None, numa_aux=None) -> SolveResult`` with bit-identical
    outputs to single-device ``solve_batch``/``pallas_solve_batch``
    (smallest-node-index tie-breaks included — the packed exchange
    carries global lane ids). Node-count padding: the node axis is
    padded with unschedulable zero rows to shards x 128 lanes before
    sharding; assignments are remapped back to original indices.

    On CPU (tests / the driver dryrun) the kernels run under the TPU
    interpreter with emulated remote DMAs — the same program, same
    synchronization, slower clock.
    """
    if not distributed_kernel_supported():
        raise RuntimeError(
            "distributed pallas kernel unavailable on this jax build "
            "(needs pltpu.CompilerParams, and pltpu.InterpretParams "
            "off-TPU) — use shard_solver/shard_full_solver (GSPMD scan)"
        )
    from koordinator_tpu.ops.pallas_binpack import (
        _kernel_epilogue,
        _pallas_solve,
    )
    from koordinator_tpu.ops.quota import quota_runtime

    devices = list(mesh.devices.flat)
    k = len(devices)

    def solve(state, pods, params, quota_state=None, gang_state=None,
              numa_aux=None, resv=None):
        import jax.numpy as jnp

        from koordinator_tpu.ops.pallas_binpack import (
            pallas_resv_supported,
            pallas_supported,
        )

        if not pallas_supported(params, config):
            # same guard as the single-chip kernel dispatch: scoring
            # modes the kernel does not implement must raise, not
            # silently diverge
            raise ValueError(
                "configuration not supported by the pallas kernel"
            )
        nonlocal_interpret = interpret
        if nonlocal_interpret is None:
            nonlocal_interpret = devices[0].platform != "tpu"
        use_q = quota_state is not None
        use_n = numa_aux is not None
        wsum = int(np.asarray(params.weights).sum()) or 1
        n = state.alloc.shape[0]
        # pad the node axis to shards x 128-lane multiples with
        # unschedulable zero rows (they can never win)
        n_loc = ((n + 128 * k - 1) // (128 * k)) * 128
        n_pad = n_loc * k
        if n_pad > 65536:
            raise ValueError("packed argmax carries 16 lane bits")
        use_r = resv is not None
        if use_r and not pallas_resv_supported(resv.node.shape[0], n_loc):
            raise ValueError(
                "reservation table unsupported by the sharded kernel "
                "(empty table: pass resv=None; otherwise too large) — "
                "use the sharded scan"
            )
        if use_r:
            from koordinator_tpu.ops.pallas_binpack import (
                pallas_resv_score_safe,
            )

            if not pallas_resv_score_safe(resv.node, resv.free,
                                          state.alloc):
                raise ValueError(
                    "reservation credit could overflow the packed "
                    "argmax's score budget — use the sharded scan"
                )

        def padn(a, fill=0):
            if a is None:
                return None
            pw = [(0, n_pad - n)] + [(0, 0)] * (a.ndim - 1)
            return jnp.pad(a, pw, constant_values=fill)

        state = NodeState(
            alloc=padn(state.alloc),
            used_req=padn(state.used_req),
            usage=padn(state.usage),
            prod_usage=padn(state.prod_usage),
            est_extra=padn(state.est_extra),
            prod_base=padn(state.prod_base),
            metric_fresh=padn(state.metric_fresh),
            schedulable=padn(state.schedulable),
            numa_cap=padn(state.numa_cap),
            numa_free=padn(state.numa_free),
        )
        npol = padn(numa_aux.node_policy) if use_n else None
        quota_in = None
        if use_q:
            runtime = quota_runtime(quota_state)
            quota_in = (quota_state.min, runtime, quota_state.used,
                        quota_state.np_used)
        # reservation tables are tiny [V,R]; they replicate, every shard
        # replays the same global consumption trajectory (the merged
        # winner is global), and the one-hot's lanes get the shard
        # offset inside _pallas_solve
        resv_in = (
            (resv.node, resv.free, resv.allocate_once, resv.match)
            if use_r else None
        )

        ns_spec = P(NODE_AXIS)
        rep = P()
        state_specs = NodeState(
            alloc=ns_spec, used_req=ns_spec, usage=ns_spec,
            prod_usage=ns_spec, est_extra=ns_spec, prod_base=ns_spec,
            metric_fresh=ns_spec, schedulable=ns_spec,
            numa_cap=ns_spec if use_n else None,
            numa_free=ns_spec if use_n else None,
        )
        pods_specs = jax.tree.map(lambda _: rep, pods)
        quota_specs = (rep, rep, rep, rep) if use_q else None

        def body(state_l, pods_l, params_l, quota_l, npol_l, resv_l):
            numa_in = None
            if use_n:
                numa_in = (state_l.numa_cap, state_l.numa_free, npol_l)
            new_state, assign, qused, qnp, consumed, resv_out = (
                _pallas_solve(
                    state_l, pods_l, params_l, wsum, nonlocal_interpret,
                    quota_l, numa_in, bool(config.numa_most_allocated),
                    n_shards=k, axis_name=NODE_AXIS, resv=resv_l,
                )
            )
            if consumed is None:
                consumed = jnp.zeros(assign.shape[0], bool)
            return new_state, assign, qused, qnp, consumed[None, :], resv_out

        body_sharded = _shard_map(
            body, mesh=mesh,
            in_specs=(state_specs, pods_specs,
                      jax.tree.map(lambda _: rep, params),
                      quota_specs, ns_spec if use_n else None,
                      (rep, rep, rep, rep) if use_r else None),
            out_specs=(state_specs, rep,
                       rep if use_q else None,
                       rep if use_q else None,
                       P(NODE_AXIS, None),
                       (rep, rep, rep, rep) if use_r else None),
            check_vma=False,
        )

        @partial(jax.jit, static_argnums=(), donate_argnums=())
        def run(state, pods, params, quota_in, npol, resv_in, quota_state,
                gang_state):
            new_state, assign, qused, qnp, consumed_k, resv_out = (
                body_sharded(state, pods, params, quota_in, npol, resv_in)
            )
            # the node axis was padded GLOBALLY (then sharded), and each
            # shard's width is already a 128-lane multiple, so the
            # kernel's global packed lane IS the original node index —
            # no remap needed, and tie-breaks match single-device
            consumed = consumed_k.any(axis=0) if use_n else None
            final_qstate = (
                quota_state._replace(used=qused, np_used=qnp)
                if use_q else None
            )
            result = _kernel_epilogue(
                new_state, assign, consumed, final_qstate, pods,
                gang_state, gang_state is not None, use_n,
                resv_out=resv_out,
            )
            return result

        result = run(state, pods, params, quota_in, npol, resv_in,
                     quota_state, gang_state)
        # strip node padding back off
        trim = lambda a: None if a is None else a[:n]
        return result._replace(
            node_state=NodeState(*(trim(x) for x in result.node_state))
        )

    return solve


def shard_full_solver(mesh: Mesh, config: SolverConfig = SolverConfig()):
    """Jitted FULL solve (quota admission, gang resolution, NUMA) with
    the node axis sharded — the multi-chip counterpart of
    ``ops.binpack.solve_batch(state, pods, params, config, quota_state,
    gang_state, numa=numa_aux)``.

    Node-major arrays (NodeState incl. numa inventories, NumaAux's
    node_policy) shard over ``nodes``; pod batches, quota and gang state
    replicate — quota groups and gangs are small [Q,R]/[G] tables every
    chip can hold, while the [N,R] node axis is the scaling dimension.
    GSPMD inserts the cross-shard argmax and the segment reductions of
    the gang epilogue. Optional features are trace-time static: pass
    None to drop a subsystem (a separate program per combination, as in
    the single-chip path).
    """
    from koordinator_tpu.ops.binpack import NumaAux, solve_batch

    ns = node_sharding(mesh)
    rep = replicated(mesh)
    jit_full = DEVICE_OBS.jit("shard_full_solver", jax.jit(
        lambda s, p, pr, q, g, x, r, n: solve_batch(
            s, p, pr, config, q, g, extras=x, resv=r, numa=n
        ),
        static_argnums=(), donate_argnums=(),
    ))

    def solve(state, pods, params, quota_state=None, gang_state=None,
              numa_aux=None, extras=None, resv=None):
        state = shard_node_state(state, mesh)
        pods = jax.device_put(pods, rep)
        params = jax.device_put(params, rep)
        if quota_state is not None:
            quota_state = jax.device_put(quota_state, rep)
        if gang_state is not None:
            gang_state = jax.device_put(gang_state, rep)
        if extras is not None:
            extras = jax.device_put(extras, rep)
        if resv is not None:
            # reservation tables are tiny [V,R]; replicate them and let
            # GSPMD gather/scatter the per-node credit against the
            # sharded used_req
            resv = jax.device_put(resv, rep)
        if numa_aux is not None:
            numa_aux = NumaAux(
                node_policy=jax.device_put(numa_aux.node_policy, ns)
            )
        return jit_full(state, pods, params, quota_state, gang_state,
                        extras, resv, numa_aux)

    return solve
