"""Device-mesh parallelism for the placement solver.

The "long axis" of this workload is the node dimension of the placement
matrices (SURVEY.md §5.7): 5k-node × R-resource tensors are sharded across
the chips of a pod slice; scoring is embarrassingly parallel over node
shards and the per-pod argmax reduction rides ICI collectives inserted by
GSPMD. This is the framework's data-parallel axis — the analogue of the
reference's node-parallel Filter/Score fan-out
(pkg/util/parallelize/parallelism.go).
"""

from koordinator_tpu.parallel.mesh import (  # noqa: F401
    NODE_AXIS,
    POD_AXIS,
    lane_sharding,
    make_mesh,
    make_mesh2d,
    node_shard_count,
    node_sharding,
    pad_node_arrays,
    pow2_quarter_bucket,
    shard_lane_solver,
    shard_node_bucket,
    shard_solver,
    shard_tenant_solver,
    stack_node_states,
    stack_pod_lanes,
)
