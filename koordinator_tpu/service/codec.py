"""Wire format for the solver boundary.

Framing: 4-byte big-endian payload length, then the payload. Payloads are
npz archives (zip of npy buffers) — a stable, language-neutral container
(C++ can read npy headers with ~50 lines; Go has cnpy-style readers), so
the control plane doesn't need Python to speak to the solver. The
request carries exactly the batched Score/Reserve inputs
(NodeState/PodBatch/ScoreParams columns); the response carries the
assignments plus the mutated node accounting columns so the caller's
cache can assume without re-deriving.
"""

from __future__ import annotations

import dataclasses
import io
import struct
from typing import BinaryIO, Dict, Optional

import numpy as np

_LEN = struct.Struct(">I")
MAX_FRAME = 1 << 30


@dataclasses.dataclass
class SolveRequest:
    """One batched solve: the scan's inputs as host arrays."""

    node: Dict[str, np.ndarray]    # alloc/used_req/usage/... [N,R]+masks
    pods: Dict[str, np.ndarray]    # req/est/is_prod/... [P,...]
    params: Dict[str, np.ndarray]  # weights/thresholds/prod_thresholds [R]


@dataclasses.dataclass
class SolveResponse:
    assignments: np.ndarray              # [P] int32 node index or -1
    node_used_req: Optional[np.ndarray] = None  # [N,R] post-solve
    error: str = ""


def write_frame(stream: BinaryIO, payload: bytes) -> None:
    stream.write(_LEN.pack(len(payload)))
    stream.write(payload)


def read_frame(stream: BinaryIO) -> Optional[bytes]:
    header = stream.read(_LEN.size)
    if len(header) < _LEN.size:
        return None  # peer closed
    (length,) = _LEN.unpack(header)
    if length > MAX_FRAME:
        raise ValueError(f"frame too large: {length}")
    chunks = []
    remaining = length
    while remaining:
        chunk = stream.read(remaining)
        if not chunk:
            raise EOFError("truncated frame")
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def _pack(arrays: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _unpack(payload: bytes) -> Dict[str, np.ndarray]:
    with np.load(io.BytesIO(payload), allow_pickle=False) as z:
        return {k: z[k] for k in z.files}


def encode_request(req: SolveRequest) -> bytes:
    arrays: Dict[str, np.ndarray] = {}
    for prefix, group in (("n.", req.node), ("p.", req.pods), ("s.", req.params)):
        for key, value in group.items():
            arrays[prefix + key] = np.asarray(value)
    return _pack(arrays)


def decode_request(payload: bytes) -> SolveRequest:
    node: Dict[str, np.ndarray] = {}
    pods: Dict[str, np.ndarray] = {}
    params: Dict[str, np.ndarray] = {}
    for key, value in _unpack(payload).items():
        prefix, name = key[:2], key[2:]
        {"n.": node, "p.": pods, "s.": params}[prefix][name] = value
    return SolveRequest(node=node, pods=pods, params=params)


def encode_response(resp: SolveResponse) -> bytes:
    arrays = {
        "assignments": np.asarray(resp.assignments, dtype=np.int32),
        "error": np.frombuffer(resp.error.encode(), dtype=np.uint8),
    }
    if resp.node_used_req is not None:
        arrays["node_used_req"] = np.asarray(resp.node_used_req)
    return _pack(arrays)


def decode_response(payload: bytes) -> SolveResponse:
    arrays = _unpack(payload)
    return SolveResponse(
        assignments=arrays["assignments"],
        node_used_req=arrays.get("node_used_req"),
        error=bytes(arrays["error"]).decode() if "error" in arrays else "",
    )
