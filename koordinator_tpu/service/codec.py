"""Wire format for the solver boundary.

Framing: 4-byte big-endian payload length, then the payload. Payloads are
npz archives (zip of npy buffers) — a stable, language-neutral container
(C++ can read npy headers with ~50 lines; Go has cnpy-style readers), so
the control plane doesn't need Python to speak to the solver. The
request carries exactly the batched Score/Reserve inputs
(NodeState/PodBatch/ScoreParams columns); the response carries the
assignments plus the mutated node accounting columns so the caller's
cache can assume without re-deriving.

Why no native fast path: measured r5 at the flagship frame (1.6 MiB),
encode is 1.2 ms and decode 2.0 ms against an ~85 ms solve — the numpy
path is already memcpy+crc32 in C under the hood, so a C++ codec would
buy ~2 ms on a 90 ms round. Native effort goes where it pays
(native/perf_group.cpp's perf_event_open group reader has no Python
equivalent at all).
"""

from __future__ import annotations

import dataclasses
import io
import struct
from typing import BinaryIO, Dict, Optional

import numpy as np

_LEN = struct.Struct(">I")
MAX_FRAME = 1 << 30
#: wire protocol revision: 2 added the optional ``admission`` group
#: (deadline + QoS lane) and unknown-prefix-tolerant request decoding;
#: 3 added the optional ``trace`` group (round + span id) so
#: sidecar-side spans join the scheduler's trace — tolerated as an
#: unknown prefix by v2 servers exactly like ``admission`` was by v1.
PROTOCOL_VERSION = 3


class CodecError(ValueError):
    """The payload is not a well-formed wire message (not an npz
    archive, wrong member types, required arrays missing). The FRAME
    boundary was still read cleanly, so the caller knows exactly how
    many bytes the bad message occupied — a server keeps the
    connection, a client may retry after reconnecting."""


class FrameTooLarge(CodecError):
    """The length prefix exceeds the frame cap. Raised BEFORE any
    payload allocation: a hostile or corrupted 4-byte header can never
    make the peer buffer gigabytes."""


class TruncatedFrame(EOFError):
    """The peer died (or was cut) mid-frame: the length prefix promised
    more bytes than the stream delivered. Subclasses ``EOFError`` so
    pre-existing handlers keep working; new code should catch this and
    treat the connection as lost, never the payload as data."""


@dataclasses.dataclass
class SolveRequest:
    """One batched solve: the scan's inputs as host arrays.

    The optional groups mirror ``solve_batch``'s optional feature states
    (quota admission, gang resolution, host extras, reservation credit,
    NUMA aux) plus the static SolverConfig scalars — absent groups mean
    the plain path, so old plain requests decode unchanged."""

    node: Dict[str, np.ndarray]    # alloc/used_req/usage/... [N,R]+masks
    pods: Dict[str, np.ndarray]    # req/est/is_prod/... [P,...]
    params: Dict[str, np.ndarray]  # weights/thresholds/prod_thresholds [R]
    quota: Optional[Dict[str, np.ndarray]] = None   # QuotaState fields
    gang: Optional[Dict[str, np.ndarray]] = None    # GangState fields
    extras: Optional[Dict[str, np.ndarray]] = None  # Extras fields
    resv: Optional[Dict[str, np.ndarray]] = None    # ResvArrays fields
    numa: Optional[Dict[str, np.ndarray]] = None    # NumaAux fields
    config: Optional[Dict[str, np.ndarray]] = None  # SolverConfig scalars
    #: incremental node staging (the steady-state bandwidth win): with a
    #: full ``node`` group, ``{"epoch": k}`` asks the server to cache the
    #: staged state as delta base k; WITHOUT a ``node`` group it carries
    #: ``idx [D]`` + a row update per node field + ``base_epoch``/
    #: ``epoch``, patching the server's cached base instead of
    #: re-shipping all eight [N,R] arrays. A server that lost the base
    #: answers with a ``delta-base-mismatch`` error and the client
    #: re-establishes with a full request.
    node_delta: Optional[Dict[str, np.ndarray]] = None
    #: admission-gate metadata (wire v2): ``deadline_s`` (float64 scalar,
    #: the caller's remaining latency budget — the server sheds the
    #: request with a typed ``deadline-exceeded`` error instead of
    #: solving work the caller already abandoned) and ``lane`` (int64
    #: QoS-lane code, service/admission.py LANE_*). Absent means "no
    #: deadline, latency-sensitive lane", so v1 clients ride through
    #: unchanged; from v2 on, decode skips unknown prefixes so future
    #: groups degrade the same way (a v2 client against a v1 server
    #: gets that server's typed "decode failed" error, not a hang).
    #: The multi-tenant pool (DESIGN §20) adds ``tenant`` (utf-8 bytes
    #: as a uint8 array, service/tenancy.tenant_wire_value): the
    #: front-end's identity, scoping coalescing / delta bases /
    #: fair-share accounting per tenant. Absent means the implicit
    #: single-tenant ``default`` — an unknown key inside a known group
    #: is simply extra npz members to old servers, so this needed no
    #: protocol revision.
    admission: Optional[Dict[str, np.ndarray]] = None
    #: trace context (wire v3): ``round`` (int64, the scheduler's trace
    #: round number) and ``span`` (int64, a scheduler-unique span id).
    #: The sidecar tags its queue-wait/solve spans with the pair so one
    #: Perfetto load shows the scheduler round AND its sidecar half as
    #: one trace (obs/trace.py). Absent means an untraced (or older)
    #: client; like ``admission``, unknown to old servers and skipped.
    trace: Optional[Dict[str, np.ndarray]] = None


@dataclasses.dataclass
class SolveResponse:
    """Everything the control plane's epilogue consumes (the SolveResult
    columns models/placement.py reads after a solve)."""

    assignments: np.ndarray              # [P] int32 node index or -1
    node_used_req: Optional[np.ndarray] = None  # [N,R] post-solve
    error: str = ""
    commit: Optional[np.ndarray] = None      # [P] bool
    waiting: Optional[np.ndarray] = None     # [P] bool
    rejected: Optional[np.ndarray] = None    # [P] bool
    raw_assign: Optional[np.ndarray] = None  # [P] int32 pre-gang placement
    resv_vstar: Optional[np.ndarray] = None  # [P] int32 consumed resv, -1
    resv_delta: Optional[np.ndarray] = None  # [P,R] consumed amount


def write_frame(stream: BinaryIO, payload: bytes) -> None:
    stream.write(_LEN.pack(len(payload)))
    stream.write(payload)


def read_frame(stream: BinaryIO,
               max_frame: int = MAX_FRAME) -> Optional[bytes]:
    header = stream.read(_LEN.size)
    if len(header) < _LEN.size:
        return None  # peer closed
    (length,) = _LEN.unpack(header)
    if length > max_frame:
        raise FrameTooLarge(f"frame too large: {length} > {max_frame}")
    chunks = []
    remaining = length
    while remaining:
        chunk = stream.read(remaining)
        if not chunk:
            raise TruncatedFrame(
                f"truncated frame: peer closed {remaining} bytes short "
                f"of the {length}-byte payload"
            )
        chunks.append(chunk)
        remaining -= len(chunk)
    return b"".join(chunks)


def _pack(arrays: Dict[str, np.ndarray]) -> bytes:
    buf = io.BytesIO()
    np.savez(buf, **arrays)
    return buf.getvalue()


def _unpack(payload: bytes) -> Dict[str, np.ndarray]:
    # any payload defect — not a zip, bad npy headers, members whose
    # declared shape outruns their data — must surface as ONE typed
    # error, never a hang or a raw zipfile/numpy internal
    try:
        with np.load(io.BytesIO(payload), allow_pickle=False) as z:
            return {k: z[k] for k in z.files}
    except Exception as e:
        raise CodecError(
            f"malformed npz payload: {type(e).__name__}: {e}"
        ) from e


#: request group -> wire prefix (single-char + "."); optional groups are
#: simply absent from the archive when None
_REQ_GROUPS = (
    ("node", "n."), ("pods", "p."), ("params", "s."), ("quota", "q."),
    ("gang", "g."), ("extras", "x."), ("resv", "r."), ("numa", "u."),
    ("config", "c."), ("node_delta", "d."), ("admission", "a."),
    ("trace", "t."),
)

_RESP_OPTIONAL = (
    "node_used_req", "commit", "waiting", "rejected", "raw_assign",
    "resv_vstar", "resv_delta",
)


def encode_request(req: SolveRequest) -> bytes:
    arrays: Dict[str, np.ndarray] = {}
    for field, prefix in _REQ_GROUPS:
        group = getattr(req, field)
        if group is None:
            continue
        for key, value in group.items():
            arrays[prefix + key] = np.asarray(value)
    return _pack(arrays)


def decode_request(payload: bytes) -> SolveRequest:
    by_prefix = {prefix: field for field, prefix in _REQ_GROUPS}
    groups: Dict[str, Dict[str, np.ndarray]] = {}
    for key, value in _unpack(payload).items():
        prefix, name = key[:2], key[2:]
        field = by_prefix.get(prefix)
        if field is None:
            continue  # newer-protocol group this server doesn't speak
        groups.setdefault(field, {})[name] = value
    return SolveRequest(
        node=groups.get("node", {}),
        pods=groups.get("pods", {}),
        params=groups.get("params", {}),
        **{f: groups.get(f) for f, _p in _REQ_GROUPS[3:]},
    )


def encode_response(resp: SolveResponse) -> bytes:
    arrays = {
        "assignments": np.asarray(resp.assignments, dtype=np.int32),
        "error": np.frombuffer(resp.error.encode(), dtype=np.uint8),
    }
    for field in _RESP_OPTIONAL:
        value = getattr(resp, field)
        if value is not None:
            arrays[field] = np.asarray(value)
    return _pack(arrays)


def decode_response(payload: bytes) -> SolveResponse:
    arrays = _unpack(payload)
    if "assignments" not in arrays:
        raise CodecError("response payload missing 'assignments'")
    try:
        error = bytes(arrays["error"]).decode() if "error" in arrays else ""
    except UnicodeDecodeError as e:
        raise CodecError(f"undecodable error string: {e}") from e
    return SolveResponse(
        assignments=arrays["assignments"],
        error=error,
        **{f: arrays.get(f) for f in _RESP_OPTIONAL},
    )
