"""Admission gate: the QoS-laned, deadline-aware micro-batching front
end of the solver sidecar.

The sidecar used to run one ``solve_from_request`` per connection
thread: under concurrent clients, solves contended on the device
serially through the jit cache with no queueing discipline, no
deadlines, and no overload behavior. This module fronts every solve
with a bounded priority queue drained by a SINGLE executor thread —
the shape continuous-batching inference servers converged on, mapped
onto Koordinator's own QoS-class hierarchy:

- **Lanes.** Three FIFO lanes in strict priority order — ``system`` >
  ``ls`` (latency-sensitive) > ``be`` (best-effort), mirroring
  apis/extension.QoSClass. A request's lane rides the wire in the
  optional ``admission`` group (codec v2); absent means ``ls``.
- **Deadlines.** ``deadline_s`` is the caller's remaining latency
  budget. A request still queued when its budget runs out is answered
  with a typed ``deadline-exceeded`` error instead of solving work the
  caller already abandoned (and instead of silence).
- **Shedding.** The queue is bounded (``AdmissionConfig.capacity``).
  When full, best-effort entries are shed FIRST: an arriving
  higher-lane request evicts the newest entry of the lowest-priority
  non-empty lane strictly below it; an arrival that outranks nothing
  is itself refused. Shed requests get a typed ``overloaded`` error
  the client can back off on (service/client.RemoteSolver does, with
  jittered exponential backoff under a total-deadline cap).
- **Coalescing.** Concurrent requests that share a node-state base —
  same full-state fingerprint over the staged node columns, params,
  config, and pod schema, salted with the TENANT identity so two
  tenants' byte-identical worlds never merge — are merged into ONE
  device dispatch: each caller's pod rows become one lane of a
  ``jax.vmap``-stacked batch over the shared staged base, so every
  scan step's [N,R] work vectorizes ACROSS callers instead of
  serializing them. The solver is integer arithmetic end to end, so
  the split-back responses are bit-identical to K solves run one at a
  time against the same staged state — K waiting clients cost one
  device dispatch instead of K. The dispatch is assignments-only
  (``want_state=False``): the [K,N,R] per-lane state carry was
  measured dead weight on the gate path (PR 15: its allocator churn is
  3–10x timing noise at small K), so coalesced responses carry
  placements, not ``node_used_req``. Only plain requests (no
  quota/gang/resv/numa/extras/delta groups) coalesce; everything else
  rides the solo path through ``solve_from_request`` unchanged.
- **Cross-tenant lane batching** (the multi-tenant pool, DESIGN §20).
  Plain requests from DIFFERENT tenants that share a *shape bucket*
  (service/tenancy.shape_bucket_key: node/pod buckets + schema +
  static config — no data) batch as lanes of ONE multi-base dispatch:
  every lane carries its own staged world and params
  (tenancy.solve_tenant_lanes). A weighted-fair allocator splits the
  dispatch window's lane budget when tenants contend
  (tenancy.allocate_fair_lanes), shedding respects per-tenant fair
  shares (one tenant's burst can only evict tenants OVER their share,
  or itself), and all shed/deadline/depth accounting is kept — and
  exported — per tenant.

The gate deliberately serializes solves on one thread: the device is a
serial resource, and a single drainer turns N racing handler threads
into one well-ordered dispatch stream with an explicit queue to
measure (wait/solve histograms, per-lane depth gauges, shed counters —
metrics/components.SOLVER_METRICS, served by ``--debug-port``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.apis.extension import QoSClass
from koordinator_tpu.obs.device import DEVICE_OBS
from koordinator_tpu.obs.trace import TRACER
from koordinator_tpu.metrics.components import (
    SOLVER_ADMISSION_BATCHES,
    SOLVER_ADMISSION_REQUESTS,
    SOLVER_ADMISSION_SHED,
    SOLVER_ADMISSION_WAIT,
    SOLVER_QUEUE_DEPTH,
    SOLVER_SOLVE_DURATION,
)
from koordinator_tpu.ops.binpack import (
    STAGED_NODE_FIELDS,
    NodeState,
    PodBatch,
    ScoreParams,
    SolverConfig,
    solve_batch,
)
from koordinator_tpu.service.codec import SolveRequest, SolveResponse
from koordinator_tpu.service.tenancy import (
    DEFAULT_TENANT,
    TenantRegistry,
    allocate_fair_lanes,
    delta_shape_key,
    fair_share,
    plain_request,
    request_tenant,
    shape_bucket_key,
    solve_entry_lanes,
)

# -- lanes ------------------------------------------------------------------

LANE_SYSTEM = 0
LANE_LS = 1
LANE_BE = 2
LANE_NAMES = ("system", "ls", "be")
LANE_BY_NAME = {name: i for i, name in enumerate(LANE_NAMES)}

# -- typed shed/overload errors (SolveResponse.error prefixes) --------------

ERR_OVERLOADED = "overloaded"
ERR_DEADLINE = "deadline-exceeded"
ERR_SHUTDOWN = "shutting-down"
ERR_INTERNAL = "internal"


def lane_for_qos(qos: QoSClass) -> int:
    """QoSClass -> admission lane (system > latency-sensitive > BE)."""
    if qos == QoSClass.SYSTEM:
        return LANE_SYSTEM
    if qos == QoSClass.BE:
        return LANE_BE
    return LANE_LS


def error_response(kind: str, detail: str) -> SolveResponse:
    """A typed error frame: ``kind`` is the machine-readable prefix the
    client dispatches on (overloaded / deadline-exceeded / shutting-down)."""
    return SolveResponse(
        assignments=np.empty(0, np.int32), error=f"{kind}: {detail}"
    )


def request_lane(req: SolveRequest) -> int:
    """The wire lane code, defaulting to latency-sensitive (absent
    admission group = v1 client)."""
    adm = req.admission
    if not adm or "lane" not in adm:
        return LANE_LS
    try:
        lane = int(np.asarray(adm["lane"]).item())
    except (TypeError, ValueError):
        return LANE_LS
    return lane if 0 <= lane < len(LANE_NAMES) else LANE_LS


def request_deadline_s(req: SolveRequest) -> Optional[float]:
    adm = req.admission
    if not adm or "deadline_s" not in adm:
        return None
    try:
        d = float(np.asarray(adm["deadline_s"]).item())
    except (TypeError, ValueError):
        return None
    return d if d >= 0 else 0.0


# -- coalescing -------------------------------------------------------------

#: params every solve must carry (ScoreParams schema); the full
#: request plainness predicate lives in service/tenancy.plain_request
#: (shared with the shape-bucket key so the two batching tiers can
#: never disagree on what may batch)
_PARAM_FIELDS = ScoreParams._fields


def coalesce_key(req: SolveRequest) -> Optional[bytes]:
    """Full-state fingerprint of a PLAIN request, or None when the
    request must ride the solo path.

    Two requests with equal keys see byte-identical staged bases
    (node columns + params + config + pod schema/dtypes) AND belong to
    the same tenant — the tenant identity salts the hash, so two
    tenants shipping byte-identical worlds still never merge into one
    base (the multi-tenant isolation contract, DESIGN §20; they may
    still share a dispatch as separate lanes with separate bases).
    Delta-protocol requests never coalesce: they patch per-connection
    cached state, which is connection-ordered by construction."""
    if not plain_request(req):
        return None
    h = hashlib.blake2b(digest_size=16)
    h.update(b"tenant:")
    h.update(request_tenant(req).encode("utf-8"))

    def feed(tag: str, a: np.ndarray, data: bool = True) -> None:
        h.update(tag.encode())
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape).encode())
        if data:
            h.update(np.ascontiguousarray(a).tobytes())

    for f in STAGED_NODE_FIELDS:
        feed(f, np.asarray(req.node[f]))
    for f in sorted(req.params):
        feed("s." + f, np.asarray(req.params[f]))
    if req.config is not None:
        for f in sorted(req.config):
            feed("c." + f, np.asarray(req.config[f]))
    for f in sorted(req.pods):
        # pod schema only: values differ per caller (that's the point),
        # but dtype/trailing dims must agree for the concat to stage
        # the same program an isolated solve would
        a = np.asarray(req.pods[f])
        h.update(("p." + f).encode())
        h.update(str(a.dtype).encode())
        h.update(repr(a.shape[1:]).encode())
    return h.digest()


def coalesce_pod_bucket(p: int) -> int:
    """The coalesced lane stack's pod-axis bucket (next power of two,
    floor 8): the largest request in the batch is padded here and every
    lane rides that width. A named member of the repo bucket family
    (docs/DESIGN.md §23) so graftcheck's shape-flow passes can
    enumerate its finite image — the math is unchanged from the PR 8
    inline form, bit for bit."""
    return max(8, 1 << max(0, p - 1).bit_length())


def _vmapped_plain_solve(state, pods, params, config):
    """K independent plain solves against one shared base, as ONE XLA
    program: ``pods`` carries a leading request axis; the scan runs per
    lane with every step's [N,R] work vectorized ACROSS lanes — unlike
    concatenating pod rows into one long scan, which would serialize K
    callers' compute (measured 2.4-8x slower on CPU at bench shapes)."""
    return jax.vmap(
        lambda p: solve_batch(state, p, params, config)
    )(pods)


def _vmapped_plain_assign(state, pods, params, config):
    """The assignments-only twin — the GATE's dispatch: plain solves
    commit exactly their placed pods (``commit == assign >= 0``,
    waiting/rejected all-False), so placements are the whole result and
    the [K,N,R] per-lane state carry stays unmaterialized. PR 15
    measured that carry's allocator churn at 3–10x timing noise for
    small K — dead weight on the serving path."""
    return jax.vmap(
        lambda p: solve_batch(state, p, params, config).assign
    )(pods)


#: the coalesced dispatches: one jitted program per (K, pod-bucket, N)
#: shape, shared by every gate in the process (static config hashes per
#: value; nothing donated — the base is reused lane-to-lane and by
#: later batches). The full-state variant serves ``want_state=True``
#: callers (isolation property tests); the gate runs assignments-only.
_jit_coalesced = DEVICE_OBS.jit("coalesced_solve", jax.jit(
    _vmapped_plain_solve, static_argnames=("config",), donate_argnums=()
))
_jit_coalesced_assign = DEVICE_OBS.jit("coalesced_solve_assign", jax.jit(
    _vmapped_plain_assign, static_argnames=("config",), donate_argnums=()
))
# AOT warm pool (docs/DESIGN.md §21): the gate's coalesced dispatches
# join the manifest like the solo sidecar solve — a respawned pooled
# sidecar's first coalesced burst restores the stacked program instead
# of cold-compiling. Never donates (§19.2; graftcheck-pinned adopts).
from koordinator_tpu.service.warmpool import WARM_POOL  # noqa: E402

WARM_POOL.adopt(_jit_coalesced, _vmapped_plain_solve, config_argpos=3)
WARM_POOL.adopt(_jit_coalesced_assign, _vmapped_plain_assign,
                config_argpos=3)


def solve_coalesced(
    requests: Sequence[SolveRequest],
    config: Optional[SolverConfig] = SolverConfig(),
    want_state: bool = False,
) -> List[SolveResponse]:
    """Solve K same-base plain requests in ONE device dispatch and split
    the results back per caller.

    Each caller's pod rows become one lane of a ``[K, P*, ...]`` stack
    (``P*`` = the largest request padded to a power-of-two bucket, so
    drifting sizes reuse compiled programs; padding rows are
    ``blocked`` — they place nothing and mutate no state). The solver
    is integer arithmetic end to end, so the vmapped lanes are
    bit-identical to K isolated solves: each returned
    ``SolveResponse`` matches what ``solve_from_request`` would have
    produced for that request alone. The default dispatch is
    assignments-only; ``want_state=True`` additionally materializes the
    per-lane final ``node_used_req`` (the [K,N,R] carry the gate path
    deliberately skips)."""
    head = requests[0]
    if config is None:
        config = SolverConfig()
    if head.config is not None:
        from koordinator_tpu.service.server import _decode_config

        config = _decode_config(head.config)
    state = NodeState(
        **{f: jnp.asarray(head.node[f]) for f in STAGED_NODE_FIELDS}
    )
    params = ScoreParams(
        **{f: jnp.asarray(head.params[f]) for f in _PARAM_FIELDS}
    )
    counts = [int(np.asarray(r.pods["req"]).shape[0]) for r in requests]
    bucket = coalesce_pod_bucket(max(counts))
    # the coalesced lane stack's bucket padding, reported like every
    # other pow2 staging buffer (docs/DESIGN.md §17)
    DEVICE_OBS.note_padding(
        "coalesced_pods", sum(counts), len(requests) * bucket
    )
    fields = sorted(set(head.pods) - {"blocked"})
    cols: Dict[str, np.ndarray] = {}
    for f in fields:
        lanes = []
        for r, n in zip(requests, counts):
            a = np.asarray(r.pods[f])
            if n < bucket:
                a = np.concatenate([
                    a, np.zeros((bucket - n,) + a.shape[1:], a.dtype)
                ])
            lanes.append(a)
        cols[f] = np.stack(lanes)
    blocked = np.ones((len(requests), bucket), bool)
    for k, (r, n) in enumerate(zip(requests, counts)):
        blocked[k, :n] = (
            np.asarray(r.pods["blocked"]) if "blocked" in r.pods
            else False
        )
    pods = PodBatch.build(
        blocked=jnp.asarray(blocked),
        **{f: jnp.asarray(v) for f, v in cols.items()},
    )
    # config rides POSITIONALLY (jax resolves static_argnames to
    # argnums): the warm pool answers only kwarg-free calls, and this
    # is the call shape its persisted AOT programs expect
    if want_state:
        result = _jit_coalesced(state, pods, params, config)
        assign_all = np.asarray(result.assign)
        used_all = np.asarray(result.node_state.used_req)
    else:
        assign_all = np.asarray(
            _jit_coalesced_assign(state, pods, params, config)
        )
        used_all = None
    out: List[SolveResponse] = []
    for k, n in enumerate(counts):
        assign = np.asarray(assign_all[k, :n], np.int32)
        out.append(SolveResponse(
            assignments=assign,
            node_used_req=None if used_all is None else used_all[k],
            # plain solves commit exactly their placed pods (the gang
            # epilogue that could hold/reject never runs on this path)
            commit=assign >= 0,
            waiting=np.zeros(n, bool),
            rejected=np.zeros(n, bool),
            raw_assign=assign,
        ))
    return out


def _publish_depth(depths: Dict[str, List[int]]) -> None:
    """Per-(lane, tenant) depth gauges, from a snapshot taken under the
    gate lock (the gauges themselves tolerate benign publish races).
    ``depths`` maps every tenant the gate has ever seen to its per-lane
    counts — tenants with nothing queued publish zeros, so a drained
    tenant's series falls back to 0 instead of freezing."""
    for tenant, lanes in depths.items():
        for i, n in enumerate(lanes):
            SOLVER_QUEUE_DEPTH.set(
                n, {"lane": LANE_NAMES[i], "tenant": tenant}
            )


# -- the gate ---------------------------------------------------------------

@dataclasses.dataclass(frozen=True)
class AdmissionConfig:
    """Gate sizing. ``capacity`` bounds TOTAL queued entries across
    lanes; ``max_coalesce`` caps requests per device batch (1 disables
    coalescing); ``max_coalesced_pods`` caps the summed pod axis so one
    batch can't stage an unboundedly large lane stack.

    ``coalesce_window_s`` is the micro-batching window: when a claimed
    head is coalescible and the batch is not yet full, the executor
    lingers this long for same-base stragglers before dispatching —
    the classic continuous-batching latency-for-throughput trade. It
    only ever applies to coalescible (plain full-state) requests; the
    delta-protocol steady state and feature-group solves never wait.
    10ms is the measured knee of the 8-client bench leg (smaller
    windows miss stragglers still decoding their frames, larger ones
    pay more than the fused dispatch saves).

    ``tenant_lanes`` enables cross-tenant lane batching (DESIGN §20):
    plain requests from different tenants sharing a shape bucket join
    one multi-base dispatch, the lane budget (``max_coalesce``)
    arbitrated weighted-fair across tenants. Off, tenants still get
    per-tenant accounting and fair-share shedding, but each tenant's
    requests dispatch separately (the solo-sidecar-per-tenant
    behavior, kept as the bench baseline)."""

    capacity: int = 128
    max_coalesce: int = 16
    max_coalesced_pods: int = 4096
    coalesce_window_s: float = 0.010
    tenant_lanes: bool = True


class AdmissionEntry:
    """One queued request: the handler thread parks on :meth:`wait`
    while the executor (or the shed path) fills :attr:`response`."""

    __slots__ = (
        "request", "config", "node_cache", "lane", "deadline",
        "enqueued_at", "key", "pods_n", "response", "_done", "_gate",
        "trace_t0", "tenant", "shape_key",
    )

    def __init__(self, request, config, node_cache, lane, deadline,
                 key, pods_n, enqueued_at, gate, tenant=DEFAULT_TENANT,
                 shape_key=None):
        self.request = request
        self.config = config
        self.node_cache = node_cache
        self.lane = lane
        self.deadline = deadline
        self.enqueued_at = enqueued_at
        self.key = key
        self.pods_n = pods_n
        self.tenant = tenant
        #: the cross-tenant batching bucket (tenancy.shape_bucket_key):
        #: equal shape keys may share one multi-base lane dispatch
        self.shape_key = shape_key
        self.response: Optional[SolveResponse] = None
        self._done = threading.Event()
        self._gate = gate
        #: tracer-clock enqueue stamp (the gate's own ``clock`` may be
        #: a test fake; spans need the tracer base): queue-wait spans
        #: are emitted retroactively from this at dispatch
        self.trace_t0 = TRACER.now()

    def wait(self, timeout: Optional[float] = None) -> Optional[SolveResponse]:
        """Block until the gate answers (None only on timeout)."""
        self._done.wait(timeout)
        return self.response

    def finish(self, response: SolveResponse) -> None:
        self.response = response
        self._done.set()

    def delivered(self) -> None:
        """The handler wrote this entry's frame: unblocks the
        shutdown drain's bounded delivery wait."""
        self._gate._mark_delivered()


class AdmissionGate:
    """The bounded, QoS-laned queue + its single executor thread.

    ``solve_fn(request, solver_config, node_cache) -> SolveResponse``
    is the solo dispatch (the sidecar passes ``solve_from_request``, so
    kernel routing, the delta protocol, and the breaker are untouched);
    coalescible plain batches take :func:`solve_coalesced` instead.
    """

    def __init__(self, solve_fn: Callable, config: AdmissionConfig = AdmissionConfig(),
                 clock: Callable[[], float] = time.monotonic,
                 peer_count: Optional[Callable[[], int]] = None,
                 tenants: Optional[TenantRegistry] = None):
        self.cfg = config
        self._solve_fn = solve_fn
        self._clock = clock
        #: live-connection probe (the server passes one): with <= 1 peer
        #: connected nobody else CAN coalesce, so the micro-batching
        #: window is skipped and a lone client never pays it
        self._peer_count = peer_count
        #: tenant weights for fair-share shedding and the weighted-fair
        #: lane allocator; read-mostly, its own (inner) lock
        self.tenants = tenants if tenants is not None else TenantRegistry()
        #: one Condition guards every mutable structure below
        #: (graftcheck lock-discipline maps _lanes/_closed/_stats/
        #: _undelivered/_tenant_stats to it)
        self._lock = threading.Condition()
        self._lanes = [deque(), deque(), deque()]
        self._closed = False
        self._undelivered = 0
        self._stats = {
            "requests": 0, "batches": 0, "coalesced_requests": 0,
            "lane_batches": 0, "lane_requests": 0,
            "shed_overloaded": 0, "shed_deadline": 0, "shed_shutdown": 0,
        }
        #: tenant -> its own copy of the overload/throughput counters —
        #: one tenant's flood must be attributable from status alone
        self._tenant_stats: Dict[str, Dict[str, int]] = {}
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="admission-gate"
        )
        self._thread.start()

    def _fold_tenant(self, tenant: str) -> str:
        """Bound the distinct tenants the gate tracks (call under
        ``self._lock``): past :data:`tenancy.MAX_TRACKED_TENANTS`
        distinct ids, UNREGISTERED newcomers fold into the overflow
        bucket — a client cycling unique tenant strings cannot grow
        stats rows, depth-gauge cardinality, or per-submit publishing
        without bound. Operator-registered (weighted) tenants always
        keep their own row."""
        from koordinator_tpu.service.tenancy import (
            MAX_TRACKED_TENANTS,
            OVERFLOW_TENANT,
        )

        if tenant in self._tenant_stats:
            return tenant
        if len(self._tenant_stats) < MAX_TRACKED_TENANTS:
            return tenant
        if tenant in self.tenants.snapshot():
            return tenant
        return OVERFLOW_TENANT

    def _tstat(self, tenant: str) -> Dict[str, int]:
        """Per-tenant counter row (call under ``self._lock``)."""
        row = self._tenant_stats.get(tenant)
        if row is None:
            row = self._tenant_stats[tenant] = {
                "requests": 0, "dispatched": 0, "coalesced": 0,
                "lane_batched": 0, "shed_overloaded": 0,
                "shed_deadline": 0, "shed_shutdown": 0,
            }
        return row

    def _depth_snapshot(self, only=None) -> Dict[str, List[int]]:
        """Per-(tenant, lane) queue depths (call under ``self._lock``).
        ``only`` restricts the snapshot to the named tenants — the
        submit hot path publishes just the tenants a request touched
        (arrival + victim), while the executor's per-batch snapshot
        covers every tenant ever seen so a drained tenant's gauges
        still fall back to 0."""
        depths = {
            t: [0] * len(LANE_NAMES)
            for t in (self._tenant_stats if only is None else only)
        }
        for i, q in enumerate(self._lanes):
            for e in q:
                if only is not None and e.tenant not in depths:
                    continue
                depths.setdefault(e.tenant, [0] * len(LANE_NAMES))
                depths[e.tenant][i] += 1
        return depths

    # -- enqueue (handler threads) -----------------------------------------

    def submit(self, request: SolveRequest, solver_config: SolverConfig,
               node_cache=None) -> AdmissionEntry:
        """Admit (or shed) one request; always returns an entry whose
        :meth:`AdmissionEntry.wait` yields a response — typed error
        responses included, so clients see frames, never silence."""
        now = self._clock()
        d = request_deadline_s(request)
        tenant = request_tenant(request)
        key = coalesce_key(request) if self.cfg.max_coalesce > 1 else None
        shape_key = None
        if self.cfg.max_coalesce > 1 and self.cfg.tenant_lanes:
            # plain requests batch on their wire world's shape; pure
            # delta requests batch on their STAGED base's shape (the
            # per-tenant-connection cache) — the steady-state serving
            # path. Anything else (mismatched base included) rides solo.
            shape_key = shape_bucket_key(request)
            if shape_key is None:
                shape_key = delta_shape_key(request, node_cache)
        try:
            pods_n = int(np.asarray(request.pods["req"]).shape[0])
        except (KeyError, IndexError, AttributeError):
            pods_n = 0
        entry = AdmissionEntry(
            request, solver_config, node_cache, request_lane(request),
            None if d is None else now + d, key, pods_n, now, self,
            tenant=tenant, shape_key=shape_key,
        )
        victim: Optional[AdmissionEntry] = None
        rejected: Optional[str] = None
        with self._lock:
            self._undelivered += 1
            # identity may fold into the overflow bucket past the
            # tracked-tenant cap: accounting AND fairness then treat
            # the folded tenants as one principal (a deliberate bound —
            # per-tenant fair-share precision is promised for
            # registered tenants and the first MAX_TRACKED_TENANTS
            # ad-hoc ones, not for unbounded id churn). The COALESCE
            # key above keeps the true wire id: staged BASES never
            # merge across folded tenants.
            tenant = entry.tenant = self._fold_tenant(tenant)
            self._tstat(tenant)["requests"] += 1
            if self._closed:
                rejected = ERR_SHUTDOWN
            else:
                if sum(len(q) for q in self._lanes) >= self.cfg.capacity:
                    victim = self._pick_victim(entry)
                    if victim is None:
                        rejected = ERR_OVERLOADED
                if rejected is None:
                    self._lanes[entry.lane].append(entry)
                    # notify_all: the condition is shared with
                    # wait_delivered() callers — a single notify could
                    # wake one of those instead of the executor and
                    # strand the enqueued entry until the next event
                    self._lock.notify_all()
            if victim is not None:
                self._stats["shed_overloaded"] += 1
                self._tstat(victim.tenant)["shed_overloaded"] += 1
            elif rejected == ERR_OVERLOADED:
                self._stats["shed_overloaded"] += 1
                self._tstat(tenant)["shed_overloaded"] += 1
            elif rejected == ERR_SHUTDOWN:
                self._stats["shed_shutdown"] += 1
                self._tstat(tenant)["shed_shutdown"] += 1
            touched = {tenant}
            if victim is not None:
                touched.add(victim.tenant)
            depths = self._depth_snapshot(only=touched)
        _publish_depth(depths)
        if victim is not None:
            SOLVER_ADMISSION_SHED.inc(
                {"lane": LANE_NAMES[victim.lane], "reason": "overloaded",
                 "tenant": victim.tenant}
            )
            victim.finish(error_response(
                ERR_OVERLOADED,
                f"queue full ({self.cfg.capacity}); shed for a "
                f"{LANE_NAMES[entry.lane]}-lane arrival",
            ))
        if rejected is not None:
            reason = ("shutdown" if rejected == ERR_SHUTDOWN
                      else "overloaded")
            SOLVER_ADMISSION_SHED.inc(
                {"lane": LANE_NAMES[entry.lane], "reason": reason,
                 "tenant": tenant}
            )
            detail = (
                "sidecar stopping; request not solved"
                if rejected == ERR_SHUTDOWN
                else f"queue full ({self.cfg.capacity}) and no "
                     f"sheddable lower-priority entry (fair shares "
                     f"respected)"
            )
            entry.finish(error_response(rejected, detail))
        return entry

    def _pick_victim(self, entry: AdmissionEntry
                     ) -> Optional[AdmissionEntry]:
        """The overload eviction choice (call under ``self._lock``):
        newest entry of the lowest-priority non-empty lane strictly
        below the arrival — RESTRICTED to victims whose tenant is over
        its weighted fair share, or shares the arrival's tenant. A
        tenant at/under its share can never lose queued work to another
        tenant's burst (the multi-tenant isolation contract); with one
        tenant this reduces exactly to the pre-tenancy policy. Removes
        the chosen victim from its lane."""
        queued: Dict[str, int] = {}
        for q in self._lanes:
            for e in q:
                queued[e.tenant] = queued.get(e.tenant, 0) + 1
        weights = self.tenants.weights_for(
            set(queued) | {entry.tenant}
        )
        shares = fair_share(self.cfg.capacity, weights)
        for shed_lane in (LANE_BE, LANE_LS):
            if shed_lane <= entry.lane:
                continue
            for victim in reversed(self._lanes[shed_lane]):
                if (
                    victim.tenant == entry.tenant
                    or queued.get(victim.tenant, 0)
                    > shares.get(victim.tenant, 0)
                ):
                    self._lanes[shed_lane].remove(victim)
                    return victim
        return None

    # -- drain (the executor thread) ---------------------------------------

    def _poll(self):
        """Block for work; returns (expired, batch) — batch is [] when
        everything claimable had expired — or None once closed."""
        with self._lock:
            while not self._closed and not any(self._lanes):
                self._lock.wait()
            if self._closed:
                return None
            now = self._clock()
            expired: List[AdmissionEntry] = []
            for q in self._lanes:
                if not q:
                    continue
                kept = deque()
                while q:
                    e = q.popleft()
                    if e.deadline is not None and e.deadline <= now:
                        expired.append(e)
                    else:
                        kept.append(e)
                q.extend(kept)
            batch: List[AdmissionEntry] = []
            for q in self._lanes:  # strict lane priority order
                if q:
                    batch.append(q.popleft())
                    break
            if batch and (batch[0].key is not None
                          or batch[0].shape_key is not None):
                head = batch[0]
                room = self.cfg.max_coalesced_pods - head.pods_n
                window = self.cfg.coalesce_window_s
                if self._peer_count is not None and self._peer_count() <= 1:
                    window = 0.0  # lone client: no one to wait for
                window_end = now + window
                hard_end = now + 3 * window  # a trickle can't stall forever
                while True:
                    # claim every queued batchable entry — same-base
                    # (coalesce key) or same shape bucket from another
                    # tenant — then linger inside the micro-batching
                    # window for stragglers while the batch can grow.
                    # When tenants contend for the lane budget, the
                    # weighted-fair allocator splits it (DESIGN §20).
                    before = len(batch)
                    room = self._claim_batch(head, batch, room)
                    if (
                        len(batch) >= self.cfg.max_coalesce
                        or self._closed
                    ):
                        break
                    if (
                        self._peer_count is not None
                        and len(batch) >= self._peer_count()
                    ):
                        # every live connection already has an entry in
                        # this batch, and a connection carries at most
                        # one in-flight request — NOBODY can join, so
                        # the window has nothing left to buy (the
                        # N-peer generalization of the lone-client
                        # skip)
                        break
                    if len(batch) > before:
                        # arrivals are trickling in: slide the window so
                        # one late decoder doesn't force a second
                        # dispatch, but never past the hard cap
                        window_end = min(
                            hard_end, self._clock() + window
                        )
                    remaining = window_end - self._clock()
                    if remaining <= 0:
                        break
                    self._lock.wait(remaining)
            if expired:
                self._stats["shed_deadline"] += len(expired)
                for e in expired:
                    self._tstat(e.tenant)["shed_deadline"] += 1
            depths = self._depth_snapshot()
        _publish_depth(depths)
        return expired, batch

    def _claim_batch(self, head: AdmissionEntry,
                     batch: List[AdmissionEntry], room: int) -> int:
        """One claim pass (call under ``self._lock``): move every
        queued entry that can join ``head``'s dispatch into ``batch``,
        weighted-fair across tenants, and return the remaining pod
        room. Joinable: same coalesce key (same tenant, byte-identical
        base — the vmap-over-one-base shape) or, with ``tenant_lanes``,
        same shape bucket (any tenant, own base — the multi-base lane
        shape). Per-tenant claim order stays lane-priority-then-FIFO;
        the allocator only arbitrates ACROSS tenants."""
        budget = self.cfg.max_coalesce - len(batch)
        if budget <= 0:
            return room
        candidates: Dict[str, List[AdmissionEntry]] = {}
        for q in self._lanes:
            for e in q:
                same_base = e.key is not None and e.key == head.key
                same_bucket = (
                    self.cfg.tenant_lanes
                    and e.shape_key is not None
                    and e.shape_key == head.shape_key
                )
                if same_base or same_bucket:
                    candidates.setdefault(e.tenant, []).append(e)
        if not candidates:
            return room
        preloaded: Dict[str, int] = {}
        for e in batch:
            preloaded[e.tenant] = preloaded.get(e.tenant, 0) + 1
        take = allocate_fair_lanes(
            candidates, self.tenants.weight, budget, room,
            lambda e: e.pods_n, preloaded,
        )
        for e in take:
            self._lanes[e.lane].remove(e)
            batch.append(e)
            room -= e.pods_n
        return room

    def _run(self) -> None:
        while True:
            try:
                polled = self._poll()
                if polled is None:
                    return
                expired, batch = polled
                for e in expired:
                    SOLVER_ADMISSION_SHED.inc(
                        {"lane": LANE_NAMES[e.lane], "reason": "deadline",
                         "tenant": e.tenant}
                    )
                    e.finish(error_response(
                        ERR_DEADLINE,
                        "request expired in the admission queue before "
                        "dispatch",
                    ))
                if batch:
                    self._dispatch(batch)
            except Exception as exc:  # the drainer must never die:
                # a wedged executor would strand every parked handler
                import warnings

                warnings.warn(
                    f"admission gate executor error: "
                    f"{type(exc).__name__}: {exc}",
                    RuntimeWarning,
                )

    def _dispatch(self, batch: List[AdmissionEntry]) -> None:
        # function-level import like _decode_config's: server imports
        # this module at top level, so the reverse edge stays lazy
        from koordinator_tpu.service.server import _trace_args

        t0 = self._clock()
        t_dispatch = TRACER.now()
        for e in batch:
            SOLVER_ADMISSION_WAIT.observe(
                max(0.0, t0 - e.enqueued_at),
                {"lane": LANE_NAMES[e.lane], "tenant": e.tenant},
            )
            # retro queue-wait span per request, joined to the caller's
            # trace via the wire context (codec v3 ``trace`` group)
            TRACER.emit(
                "queue_wait", cat="admission", t0=e.trace_t0,
                t1=t_dispatch,
                args={"lane": LANE_NAMES[e.lane],
                      **(_trace_args(e.request) or {})},
            )
        # three dispatch shapes: solo (one request, full feature set),
        # coalesced (one tenant, one shared base, vmap lanes), tenant
        # lanes (many tenants, one base PER lane — the pool, DESIGN §20)
        if len(batch) == 1:
            mode = "solo"
        elif all(e.key is not None and e.key == batch[0].key
                 for e in batch):
            mode = "coalesced"
        else:
            mode = "lanes"
        try:
            if mode == "solo":
                e = batch[0]
                responses = [self._solve_fn(e.request, e.config, e.node_cache)]
            elif mode == "coalesced":
                responses = solve_coalesced(
                    [e.request for e in batch], batch[0].config
                )
            else:
                responses = solve_entry_lanes(batch, batch[0].config)
        except Exception as exc:  # solo path catches its own; this
            # guards the batched staging/split — callers still get a
            # typed frame, never silence
            responses = [
                error_response(
                    ERR_INTERNAL, f"{type(exc).__name__}: {exc}"
                )
            ] * len(batch)
        SOLVER_SOLVE_DURATION.observe(max(0.0, self._clock() - t0))
        TRACER.emit(
            "admission_dispatch", cat="admission", t0=t_dispatch,
            args={"coalesced": len(batch), "mode": mode,
                  **(_trace_args(batch[0].request) or {})},
        )
        SOLVER_ADMISSION_BATCHES.inc()
        SOLVER_ADMISSION_REQUESTS.inc({"mode": mode}, amount=len(batch))
        with self._lock:
            self._stats["batches"] += 1
            self._stats["requests"] += len(batch)
            if mode == "coalesced":
                self._stats["coalesced_requests"] += len(batch)
            elif mode == "lanes":
                self._stats["lane_batches"] += 1
                self._stats["lane_requests"] += len(batch)
            for e in batch:
                row = self._tstat(e.tenant)
                row["dispatched"] += 1
                if mode == "coalesced":
                    row["coalesced"] += 1
                elif mode == "lanes":
                    row["lane_batched"] += 1
        for e, r in zip(batch, responses):
            e.finish(r)

    # -- observability / shutdown ------------------------------------------

    def stats(self) -> Dict[str, object]:
        """Status snapshot for PlacementService.status(): per-lane
        depth, coalesce ratio, shed counts — and the per-tenant rows
        (queued depth, dispatch/batch/shed counters, weight) that make
        one tenant's overload attributable without touching /metrics."""
        with self._lock:
            depth = {
                LANE_NAMES[i]: len(q) for i, q in enumerate(self._lanes)
            }
            s = dict(self._stats)
            closed = self._closed
            tenant_rows = {
                t: dict(row) for t, row in self._tenant_stats.items()
            }
            depths = self._depth_snapshot()
        weights = self.tenants.weights_for(tenant_rows)
        for t, row in tenant_rows.items():
            row["queued"] = sum(depths.get(t, ()))
            row["weight"] = weights[t]
        return {
            "queue_depth": depth,
            "capacity": self.cfg.capacity,
            "max_coalesce": self.cfg.max_coalesce,
            "tenant_lanes": self.cfg.tenant_lanes,
            "requests_total": s["requests"],
            "batches_total": s["batches"],
            "coalesced_requests_total": s["coalesced_requests"],
            "lane_batches_total": s["lane_batches"],
            "lane_requests_total": s["lane_requests"],
            "coalesce_ratio": (
                s["requests"] / s["batches"] if s["batches"] else 0.0
            ),
            "shed": {
                "overloaded": s["shed_overloaded"],
                "deadline-exceeded": s["shed_deadline"],
                "shutting-down": s["shed_shutdown"],
            },
            "tenants": tenant_rows,
            "closed": closed,
        }

    def shutdown(self, timeout: float = 5.0) -> List[AdmissionEntry]:
        """Fail every queued entry with a typed ``shutting-down`` error
        and stop the executor (waiting out an in-flight solve so its
        callers still get real responses). Returns the failed entries;
        callers pair this with :meth:`wait_delivered` so handler
        threads can write the error frames before connections are
        severed."""
        with self._lock:
            self._closed = True
            drained = [e for q in self._lanes for e in q]
            for q in self._lanes:
                q.clear()
            self._stats["shed_shutdown"] += len(drained)
            for e in drained:
                self._tstat(e.tenant)["shed_shutdown"] += 1
            depths = self._depth_snapshot()
            self._lock.notify_all()
        _publish_depth(depths)
        for e in drained:
            SOLVER_ADMISSION_SHED.inc(
                {"lane": LANE_NAMES[e.lane], "reason": "shutdown",
                 "tenant": e.tenant}
            )
            e.finish(error_response(
                ERR_SHUTDOWN, "sidecar stopping; request not solved"
            ))
        self._thread.join(timeout=timeout)
        return drained

    def wait_delivered(self, timeout: float = 2.0) -> bool:
        """Block until every answered entry's frame has been written by
        its handler (bounded): the difference between clients seeing a
        typed error and seeing a connection reset."""
        deadline = self._clock() + timeout
        with self._lock:
            while self._undelivered > 0:
                remaining = deadline - self._clock()
                if remaining <= 0:
                    return False
                self._lock.wait(remaining)
            return True

    def _mark_delivered(self) -> None:
        with self._lock:
            self._undelivered -= 1
            if self._undelivered <= 0:
                self._lock.notify_all()
