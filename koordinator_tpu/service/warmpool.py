"""The AOT warm pool: restart, promotion, and failover never pay a
cold XLA compile (docs/DESIGN.md §21).

PR 15's preflight proved an AOT executable round-trip works
(``utils/compilation_cache.ExecutableCache``); this module promotes it
to a first-class recovery path. The pool sits BEHIND the existing
``DEVICE_OBS.jit`` bindings: a binding adopted via :meth:`WarmPool.
adopt` first consults the pool's in-memory executable map — a restored
entry answers the call with zero tracing and zero compilation — and
falls back to the ordinary jit on any miss. Three recovery paths ride
it:

- **Leader promotion** (``StateAuditor.note_promotion`` → the
  promotion sweep): the new leader synchronously loads the manifest's
  executables from disk (loads only — a corrupt store degrades to
  cold compile at the first solve, never blocks the promotion round)
  and eagerly restores the staged world.
- **Sidecar respawn** (``SolverSupervisor`` children): ``koord-solver``
  restores sequentially at boot, before the listen socket opens, so a
  respawned sidecar's first solve is answered by a restored
  executable instead of re-tracing + recompiling (a background
  restore would race the first reconnecting client's solve).
- **Degraded-mode flips** (``FailoverSolver``): the local twin is
  pre-compiled/pre-loaded at construction in the background, so the
  first degraded solve — the moment the watchdog used to flag — is
  warm.

**What gets warmed** is decided by the device observatory:
``DEVICE_OBS.warm_manifest()`` snapshots the hot (fn ×
aval-signature) pairs, and :meth:`WarmPool.persist` AOT-compiles each
one (off the tick path) into the on-disk store plus a framed manifest.
Entries are keyed by PROGRAM identity (the wrapped function's
qualname + static config values + array avals), not binding name — so
the sidecar's ``sidecar_solve_batch``, the in-process model's
``solve_batch``, and the failover twin ``failover_local_solve`` all
share one store: signatures recorded by a running sidecar warm the
scheduler's failover twin in another process.

**Hard rules** (DESIGN §19.2 / §21):

- *The warm path never donates.* A DONATED multi-device jit replayed
  from a persistent cache mis-applies its alias map on jax 0.4.x
  (same-shaped outputs swap; under concurrency the heap corrupts).
  Every executable the pool stores or restores is compiled with
  ``donate_argnums=()`` — structurally, in :func:`_closure_jit`, the
  only constructor of pool programs — and graftcheck's donation rule
  pins both this module and every adopt site (a donating binding can
  never be adopted).
- *Single device only.* AOT executables pin device placement; the
  pool refuses to serve (and to restore) in a multi-device process —
  which also makes the §19.2 replay bug unreachable by construction.
- *Every load failure is typed, counted, and quarantined.* The store
  lives on disk across crashes; torn, bit-flipped, oversized,
  stale-host, version-skewed, or foreign entries surface as the
  ``WarmEntryError`` family (utils/compilation_cache.py), count a
  ``scheduler_warm_pool_rejects_total`` with their reason (clean
  absences count ``..._misses_total``), move aside to
  ``*.quarantined`` (never retried in a loop), and fall back to
  cold compile. A poisoned store slows recovery; it never crashes the
  scheduler and never skips a round.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

import jax

from koordinator_tpu.metrics.components import (
    WARM_POOL_HITS,
    WARM_POOL_MISSES,
    WARM_POOL_QUARANTINED,
    WARM_POOL_REJECTS,
    WARM_RESTORE_SECONDS,
)
from koordinator_tpu.obs.device import DEVICE_OBS, WARM_MISS, _signature
from koordinator_tpu.obs.trace import TRACER
from koordinator_tpu.utils.compilation_cache import (
    ExecutableCache,
    WarmEntryCorrupt,
    WarmEntryError,
    frame_payload,
    max_entry_bytes,
    unframe_payload,
)

#: manifest rows kept/restored at most (matches DEVICE_OBS._MAX_WARM's
#: intent: the hot set, not an unbounded archive)
_MAX_MANIFEST = 128

#: background persist cadence (cmd wiring); tests drive persist() inline
_PERSIST_INTERVAL_S = 30.0


class _Registration:
    """One adopted binding: the wrapped pure function, where its static
    config argument sits in the positional call convention, and the
    program identity shared across processes and binding names."""

    __slots__ = ("fn_name", "fun", "config_argpos", "program")

    def __init__(self, fn_name: str, fun, config_argpos: int):
        self.fn_name = fn_name
        self.fun = fun
        self.config_argpos = config_argpos
        self.program = f"{fun.__module__}.{fun.__qualname__}"


def _closure_jit(fun, config_argpos: int, config):
    """The ONLY constructor of warm-pool programs: ``fun`` with its
    static config closed over, jitted with ``donate_argnums=()`` —
    donation is structurally impossible on the warm path (DESIGN
    §19.2: a donated executable replayed from a persistent store
    mis-aliases its outputs on this jax line). graftcheck's
    donation-safety rule additionally pins this file to empty
    donation declarations."""

    def bound(*arrays):
        args = arrays[:config_argpos] + (config,) + arrays[config_argpos:]
        return fun(*args)

    return jax.jit(bound, static_argnums=(), donate_argnums=())


def _config_key(config) -> tuple:
    """Static config as a hashable, serializable key component."""
    try:
        return tuple(config)
    except TypeError:
        return (repr(config),)


def _disk_key(program: str, config, sig) -> str:
    """The on-disk store key: program identity + static config values
    + the array-aval signature. Deterministic across processes on one
    host/jax build (ExecutableCache._path additionally scopes by
    backend identity and jax version)."""
    import hashlib

    body = repr((_config_key(config), sig)).encode()
    digest = hashlib.blake2b(body, digest_size=12).hexdigest()
    return f"warm|{program}|{digest}"


class WarmPool:
    """Process warm pool over an :class:`ExecutableCache` store.

    Inert until :meth:`configure` points it at a store directory (the
    test suite's empty ``KTPU_COMPILATION_CACHE_DIR`` keeps the
    singleton inert, so warm serving never leaks into unrelated
    tests). ``serving`` is a plain flag read per adopted call without
    the lock (torn read costs one ordinary jit dispatch); every other
    mutable attribute is mapped to ``_lock`` in graftcheck's
    lock-discipline registry. Slow work — AOT compiles, disk I/O —
    always runs OUTSIDE the lock, and the pool's lock never nests with
    any other mapped lock."""

    def __init__(self, cache: Optional[ExecutableCache] = None):
        #: fast-path flag: True only while at least one executable is
        #: installed AND the pool is active (plain read, like
        #: DeviceObservatory.enabled)
        self.serving = False
        self._lock = threading.Lock()
        self._cache = cache
        #: whether configure() ever ran (ensure_configured's guard —
        #: "configured but inert" must not re-configure per service)
        self._configured = cache is not None
        self._single_device: Optional[bool] = None
        self._reg: Dict[str, _Registration] = {}
        #: (program, config_key, sig) -> compiled executable. Keyed by
        #: PROGRAM identity, not binding name, so (a) bindings sharing
        #: a program (solve_batch / failover twin / sidecar) share one
        #: map and (b) a background restore can run BEFORE any binding
        #: registers — the boot path overlaps deserialization with
        #: scheduler construction
        self._execs: Dict = {}
        #: the in-flight background restore (wait_restored joins it)
        self._restore_thread: Optional[threading.Thread] = None
        #: (program, config_key, sig) already persisted (or known bad)
        self._persisted: set = set()
        #: manifest rows: (program, config_key) -> (aval_args, aval_kwargs)
        self._manifest: Dict = {}
        self.hits = 0
        #: clean store misses (no entry for the key): cold compile,
        #: nothing wrong with the store
        self.misses = 0
        #: typed rejection-ladder refusals by reason (truncated |
        #: corrupt | fingerprint | oversized | stale-host | version-skew)
        self.rejects: Dict[str, int] = {}
        self.quarantined = 0
        self.served = 0
        self.load_s_total = 0.0
        self.compiles = 0
        self.last_restore: Optional[dict] = None
        self.last_error: Optional[str] = None
        self._bg_thread: Optional[threading.Thread] = None
        self._bg_stop = threading.Event()

    # -- configuration -------------------------------------------------------

    def configure(self, cache_dir: Optional[str] = None,
                  force_single_device: Optional[bool] = None) -> "WarmPool":
        """Point the pool at a store directory (None = the
        KTPU_COMPILATION_CACHE_DIR default; an empty configured dir
        keeps the pool inert). Re-evaluates the single-device gate —
        AOT executables pin device placement, and the §19.2 replay bug
        lives in multi-device processes, so a sharded host never warm-
        serves. ``force_single_device=True`` overrides the gate for
        the test suite's forced 8-virtual-device mesh ONLY: those
        devices are one physical host, and the pool's program set
        never donates, so the replay bug is structurally absent there
        — production wiring never passes it."""
        cache = ExecutableCache(cache_dir)
        with self._lock:
            self._cache = cache if cache.dir else None
            self._configured = True
            # None = re-probe lazily (jax may init later)
            self._single_device = force_single_device
        self._refresh_serving()
        return self

    def ensure_configured(self) -> "WarmPool":
        """Configure from the environment iff :meth:`configure` never
        ran — the embedder path (a PlacementService constructed
        directly, no cmd entry point) keeps the transparent AOT
        warm-start the pre-pool per-module cache gave it. A cmd entry
        point's explicit configure always wins; the test suite's empty
        ``KTPU_COMPILATION_CACHE_DIR`` keeps this a no-op."""
        with self._lock:
            configured = self._configured
        if not configured:
            self.configure()
        return self

    @property
    def active(self) -> bool:
        """Whether the pool has a store AND may serve on this process's
        device topology."""
        with self._lock:
            cache = self._cache
        return cache is not None and self._is_single_device()

    def _is_single_device(self) -> bool:
        with self._lock:
            known = self._single_device
        if known is None:
            try:
                known = len(jax.devices()) == 1
            except Exception:
                # do NOT latch a failed probe: jax may simply not be
                # initializable yet — a transient failure must not
                # silently disable the pool for the process lifetime
                return False
            with self._lock:
                self._single_device = known
        return known

    def _refresh_serving(self) -> None:
        with self._lock:
            have = bool(self._execs) and self._cache is not None
        self.serving = have and self._is_single_device()

    def adopt(self, observed, fun, config_argpos: int) -> None:
        """Adopt a ``DEVICE_OBS.jit`` binding into the pool: record the
        program identity and hook the binding's call path so restored
        executables answer matching calls. The binding itself must have
        been constructed with ``donate_argnums=()`` — graftcheck's
        donation rule checks every adopt site against its binding."""
        reg = _Registration(observed.fn_name, fun, config_argpos)
        with self._lock:
            self._reg[observed.fn_name] = reg
        observed._warm = self

    # -- the call path -------------------------------------------------------

    def serve(self, fn_name: str, args: tuple, kwargs: dict):
        """A restored executable's answer for this call, or
        :data:`WARM_MISS`. Cost on the adopted path: one signature
        computation (~µs at solve arity) + two dict lookups under the
        lock; a process with no restored executables never reaches
        here (``serving`` gates at the binding)."""
        if kwargs:
            return WARM_MISS
        with self._lock:
            reg = self._reg.get(fn_name)
        if reg is None or len(args) <= reg.config_argpos:
            return WARM_MISS
        config = args[reg.config_argpos]
        arrays = args[: reg.config_argpos] + args[reg.config_argpos + 1:]
        try:
            key = (reg.program, _config_key(config),
                   _signature(arrays, {}))
        except TypeError:
            return WARM_MISS
        with self._lock:
            fn = self._execs.get(key)
        if fn is None:
            return WARM_MISS
        try:
            out = fn(*arrays)
        except Exception as e:
            # a stale/incompatible executable must not poison every
            # solve for this shape: drop it (the jit path takes over),
            # quarantine the DISK entry too (a call-time failure found
            # on every restart is the same retry loop the load-time
            # ladder forbids), and un-mark it persisted so the
            # background persister re-stores a fresh one
            with self._lock:
                self._execs.pop(key, None)
                self._persisted.discard(key)
                self.last_error = f"{type(e).__name__}: {e}"
                cache = self._cache
            moved = None
            if cache is not None:
                moved = cache.quarantine(
                    _disk_key(key[0], config, key[2])
                )
            if moved is not None:
                with self._lock:
                    self.quarantined += 1
                WARM_POOL_QUARANTINED.inc()
            self._refresh_serving()
            TRACER.instant("warm-pool-eject", cat="warm",
                           args={"fn": fn_name,
                                 "error": f"{type(e).__name__}"})
            return WARM_MISS
        # counted only AFTER the executable answered: an ejected call
        # that fell through to the jit must never inflate the warm
        # evidence (bench leg 17 and the chaos storm assert on served)
        with self._lock:
            self.served += 1
        return out

    # -- persist (the running leader's side) ---------------------------------

    def persist(self) -> dict:
        """Snapshot ``DEVICE_OBS.warm_manifest()`` and make the store
        cover it: every hot (program × config × signature) not yet
        persisted is AOT-compiled from its avals (one off-path backend
        compile each), stored, installed for in-process serving, and
        recorded in the on-disk manifest. Idempotent and cheap when
        nothing new compiled; called from the background thread the
        cmd entry points start (never from the tick path)."""
        if not self.active:
            return {"persisted": 0, "skipped": "inactive"}
        entries = DEVICE_OBS.warm_manifest()
        with self._lock:
            regs = dict(self._reg)
        todo: List[Tuple[_Registration, tuple, tuple, object]] = []
        for fn_name, aval_args, _aval_kwargs in entries:
            reg = regs.get(fn_name)
            if reg is None or len(aval_args) <= reg.config_argpos:
                continue
            config = aval_args[reg.config_argpos]
            arrays = (aval_args[: reg.config_argpos]
                      + aval_args[reg.config_argpos + 1:])
            try:
                sig = _signature(arrays, {})
                pkey = (reg.program, _config_key(config), sig)
            except TypeError:
                continue
            with self._lock:
                if pkey in self._persisted:
                    continue
                self._persisted.add(pkey)
            todo.append((reg, config, arrays, sig))
        persisted = 0
        for reg, config, arrays, sig in todo:
            key = _disk_key(reg.program, config, sig)
            try:
                jit_fn = _closure_jit(reg.fun, reg.config_argpos, config)
                compiled = self._get_or_compile(key, jit_fn, arrays)
            except Exception as e:
                with self._lock:
                    self.last_error = f"{type(e).__name__}: {e}"
                continue
            with self._lock:
                self._execs.setdefault(
                    (reg.program, _config_key(config), sig), compiled
                )
                self._manifest[(reg.program, _config_key(config), sig)] = (
                    (reg.config_argpos, config, arrays)
                )
            persisted += 1
        if persisted:
            self._write_manifest()
            self._refresh_serving()
            TRACER.instant("warm-pool-persist", cat="warm",
                           args={"new": persisted})
        return {"persisted": persisted}

    def _get_or_compile(self, key: str, jit_fn, arrays):
        """Load ``key`` (typed failures quarantined + counted) or
        AOT-compile from avals and store. Runs outside the lock."""
        with self._lock:
            cache = self._cache
        t0 = time.perf_counter()
        compiled = None
        try:
            compiled = cache.load_checked(key)
        except WarmEntryError as e:
            self._note_bad_entry(key, e)
        else:
            if compiled is not None:
                self._note_hit(time.perf_counter() - t0)
            else:
                self._note_miss()
        if compiled is None:
            compiled = jit_fn.lower(*arrays).compile()
            with self._lock:
                self.compiles += 1
            cache.store(key, compiled)
        return compiled

    def _note_hit(self, load_s: float) -> None:
        with self._lock:
            self.hits += 1
            self.load_s_total += load_s
        WARM_POOL_HITS.inc()

    def _note_miss(self) -> None:
        """A CLEAN miss: no entry for the key — cold compile, store
        healthy."""
        with self._lock:
            self.misses += 1
        WARM_POOL_MISSES.inc()

    def _note_reject(self, reason: str) -> None:
        with self._lock:
            self.rejects[reason] = self.rejects.get(reason, 0) + 1
        WARM_POOL_REJECTS.inc({"reason": reason})

    def _note_bad_entry(self, key: str, err: WarmEntryError) -> None:
        """A typed load failure: count the reject by reason, quarantine
        the entry (renamed aside — never retried in a loop), record
        the error for status surfaces."""
        self._note_reject(err.reason)
        with self._lock:
            self.last_error = f"{type(err).__name__}: {err}"
            cache = self._cache
        moved = cache.quarantine(key)
        if moved is not None:
            with self._lock:
                self.quarantined += 1
            WARM_POOL_QUARANTINED.inc()
        TRACER.instant("warm-pool-quarantine", cat="warm",
                       args={"reason": err.reason})

    # -- the on-disk manifest ------------------------------------------------

    def _manifest_path(self) -> Optional[str]:
        with self._lock:
            cache = self._cache
        if cache is None or not cache.dir:
            return None
        return os.path.join(cache.dir, "warm_manifest.bin")

    def _write_manifest(self) -> None:
        path = self._manifest_path()
        if path is None:
            return
        import pickle

        with self._lock:
            rows = [
                {"program": program, "config_argpos": argpos,
                 "config": config, "arrays": arrays}
                for (program, _ck, _sig), (argpos, config, arrays)
                in list(self._manifest.items())[-_MAX_MANIFEST:]
            ]
        try:
            body = pickle.dumps(rows)
            os.makedirs(os.path.dirname(path), exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(frame_payload(body))
            os.replace(tmp, path)
        except Exception as e:
            with self._lock:
                self.last_error = f"{type(e).__name__}: {e}"

    def _read_manifest(self) -> List[dict]:
        """The on-disk manifest rows; a bad manifest is typed, counted
        (reason per defect), quarantined, and returns [] — a corrupt
        manifest degrades every restore to cold, it never crashes."""
        path = self._manifest_path()
        if path is None or not os.path.exists(path):
            return []
        import pickle

        try:
            size = os.path.getsize(path)
            if size > max_entry_bytes():
                from koordinator_tpu.utils.compilation_cache import (
                    WarmEntryOversized,
                )

                raise WarmEntryOversized(f"manifest: {size}B")
            with open(path, "rb") as f:
                body = unframe_payload(f.read(), what="manifest")
            rows = pickle.loads(body)
            if not isinstance(rows, list):
                raise WarmEntryCorrupt("manifest: not a row list")
            return rows
        except WarmEntryError as e:
            self._quarantine_manifest(path, e)
            return []
        except Exception as e:
            self._quarantine_manifest(
                path, WarmEntryCorrupt(f"manifest: {type(e).__name__}: {e}")
            )
            return []

    def _quarantine_manifest(self, path: str, err: WarmEntryError) -> None:
        self._note_reject(err.reason)
        with self._lock:
            self.last_error = f"{type(err).__name__}: {err}"
        try:
            os.replace(path, f"{path}.quarantined")
        except OSError:
            return
        with self._lock:
            self.quarantined += 1
        WARM_POOL_QUARANTINED.inc()

    # -- restore (the recovering process's side) -----------------------------

    def restore(self, fns: Optional[Sequence[str]] = None,
                compile_missing: bool = False,
                background: bool = False) -> Optional[dict]:
        """Load the manifest's executables into the in-memory map for
        every adopted binding whose PROGRAM matches (``fns`` narrows to
        specific binding names). ``compile_missing=True`` additionally
        AOT-compiles entries the store cannot serve (cold, but off the
        caller's critical path when ``background=True``). Typed load
        failures quarantine + count and — without ``compile_missing``
        — simply leave that shape cold: the first real solve compiles
        as it always did. Returns the report (None when backgrounded).
        """
        if background:
            t = threading.Thread(
                target=self.restore,
                kwargs={"fns": fns, "compile_missing": compile_missing},
                daemon=True, name="warm-pool-restore",
            )
            with self._lock:
                self._restore_thread = t
            t.start()
            return None
        report = {"restored": 0, "compiled": 0, "failed": 0, "rows": 0}
        if not self.active:
            report["skipped"] = "inactive"
            with self._lock:
                self.last_restore = report
            return report
        t_start = time.perf_counter()
        rows = self._read_manifest()
        with self._lock:
            cache = self._cache
            # restoring needs NO registration (the exec map is keyed
            # by program), so the boot path can deserialize in the
            # background while the scheduler is still constructing;
            # an fns filter narrows to those bindings' programs
            programs = None if fns is None else {
                r.program for r in self._reg.values() if r.fn_name in fns
            }
            reg_funs = {r.program: r.fun for r in self._reg.values()}
        for row in rows[-_MAX_MANIFEST:]:
            try:
                program = row["program"]
                argpos = int(row["config_argpos"])
                config = row["config"]
                arrays = row["arrays"]
                sig = _signature(arrays, {})
            except Exception:
                self._note_reject("corrupt")
                report["failed"] += 1
                continue
            if programs is not None and program not in programs:
                continue
            report["rows"] += 1
            ck = _config_key(config)
            with self._lock:
                installed = (program, ck, sig) in self._execs
            if installed:
                # idempotent re-restore (boot after an early restore,
                # promotion sweeps with an unchanged store): the
                # executable is already in memory — re-deserializing
                # the same bytes would put a disk read + jax load back
                # on the recovery path for nothing
                report["restored"] += 1
                continue
            key = _disk_key(program, config, sig)
            t0 = time.perf_counter()
            try:
                compiled = cache.load_checked(key)
            except WarmEntryError as e:
                self._note_bad_entry(key, e)
                compiled = None
            else:
                if compiled is None:
                    self._note_miss()
                else:
                    self._note_hit(time.perf_counter() - t0)
            if compiled is None:
                fun = reg_funs.get(program)
                if not compile_missing or fun is None:
                    report["failed"] += 1
                    continue
                try:
                    jit_fn = _closure_jit(fun, argpos, config)
                    compiled = jit_fn.lower(*arrays).compile()
                    with self._lock:
                        self.compiles += 1
                    cache.store(key, compiled)
                    report["compiled"] += 1
                except Exception as e:
                    with self._lock:
                        self.last_error = f"{type(e).__name__}: {e}"
                    report["failed"] += 1
                    continue
                installed_cold = True
            else:
                installed_cold = False
            ck = _config_key(config)
            with self._lock:
                self._execs.setdefault((program, ck, sig), compiled)
                self._manifest[(program, ck, sig)] = (
                    argpos, config, arrays
                )
                self._persisted.add((program, ck, sig))
            if not installed_cold:
                # "restored" means DESERIALIZED (warm): a row the store
                # could not serve that compile_missing cold-compiled
                # counts ONLY under "compiled" — warm_outcome_fn readers
                # (the supervisor's probe-budget split) treat
                # restored>0 as "this child deserves the tight warm
                # grace", and a still-compiling child does not
                report["restored"] += 1
        report["wall_s"] = time.perf_counter() - t_start
        # the headline restore-latency series (boot, promotion, failover
        # prewarm): manifest read + every executable deserialization
        WARM_RESTORE_SECONDS.observe(report["wall_s"])
        self._refresh_serving()
        with self._lock:
            self.last_restore = report
        if report["restored"] or report["compiled"] or report["failed"]:
            TRACER.instant("warm-pool-restore", cat="warm", args={
                "restored": report["restored"],
                "compiled": report["compiled"],
                "failed": report["failed"],
            })
        return report

    def wait_restored(self, timeout_s: float = 60.0) -> None:
        """Join an in-flight background restore. The production boot
        paths restore SEQUENTIALLY (early, before the heavy imports —
        measured both cheaper and race-free), so this exists for
        callers that opted into ``restore(background=True)`` and must
        fence before traffic. No-op when none is running."""
        with self._lock:
            thread = self._restore_thread
        if thread is not None:
            thread.join(timeout=timeout_s)
            with self._lock:
                if self._restore_thread is thread:
                    self._restore_thread = None

    # -- background persister ------------------------------------------------

    def start_background(self,
                         interval_s: float = _PERSIST_INTERVAL_S) -> None:
        """Persist newly-observed hot signatures on a daemon thread
        (cmd entry points call this once; never on the tick path)."""
        if not self.active:
            return
        with self._lock:
            if self._bg_thread is not None and self._bg_thread.is_alive():
                return
            self._bg_stop = threading.Event()
            stop = self._bg_stop

            def _run():
                # fast cadence until the store holds SOMETHING: a
                # crash-looping process (supervisor respawns under the
                # full interval — exactly the restart-storm shape §21
                # exists for) must get its first solve's signature
                # persisted within seconds of the compile, or the
                # store stays empty forever and every respawn is cold
                delay = min(5.0, interval_s)
                while not stop.wait(delay):
                    try:
                        if self.persist().get("persisted") or \
                                self._has_store_entries():
                            delay = interval_s
                    except Exception:
                        pass  # the persister must never die loudly

            self._bg_thread = threading.Thread(
                target=_run, daemon=True, name="warm-pool-persist"
            )
            self._bg_thread.start()

    def _has_store_entries(self) -> bool:
        """Whether anything was ever persisted or restored this
        process (the persister's cadence gate)."""
        with self._lock:
            return bool(self._persisted)

    def stop_background(self) -> None:
        with self._lock:
            thread, self._bg_thread = self._bg_thread, None
            stop = self._bg_stop
        stop.set()
        if thread is not None:
            thread.join(timeout=5)

    # -- read side -----------------------------------------------------------

    def status(self) -> dict:
        """The ``warm-pool`` status/debug section (PlacementService.
        status(), both debug muxes): counters, what is installed, the
        last restore report — cheap, never compiles or touches disk."""
        with self._lock:
            programs: Dict[str, int] = {}
            for (program, _ck, _sig) in self._manifest:
                programs[program] = programs.get(program, 0) + 1
            return {
                "active": self._cache is not None,
                "serving": self.serving,
                "store_dir": None if self._cache is None
                else self._cache.dir,
                "single_device": self._single_device,
                "executables": len(self._execs),
                "registered": sorted(self._reg),
                "manifest_rows": len(self._manifest),
                # per-program row counts: the tenant-pool rows here are
                # shape-BUCKET signatures ([K*,N*,...] axes, no tenant
                # data), so "is a new tenant's first bucket warm?" is
                # answerable from one GET (ROADMAP 2b)
                "manifest_programs": programs,
                "hits": self.hits,
                "misses": self.misses,
                "rejects": dict(self.rejects),
                "served": self.served,
                "quarantined": self.quarantined,
                "compiles": self.compiles,
                "load_seconds_total": self.load_s_total,
                "last_restore": self.last_restore,
                "last_error": self.last_error,
            }

    def flight_payload(self) -> dict:
        """The flight recorder's cached ``warm`` section: was the last
        anomaly served warm or cold, and is the store healthy — from
        counters alone (a dump must not compile or touch disk)."""
        with self._lock:
            return {
                "serving": self.serving,
                "executables": len(self._execs),
                "hits": self.hits,
                "misses": self.misses,
                "rejects": dict(self.rejects),
                "served": self.served,
                "quarantined": self.quarantined,
                "last_error": self.last_error,
            }

    def reset(self) -> None:
        """Forget everything (tests)."""
        self.stop_background()
        with self._lock:
            self._execs.clear()
            self._persisted.clear()
            self._manifest.clear()
            self.hits = 0
            self.misses = 0
            self.rejects = {}
            self.quarantined = 0
            self.served = 0
            self.load_s_total = 0.0
            self.compiles = 0
            self.last_restore = None
            self.last_error = None
        self.serving = False


#: the process warm pool every adopted binding consults (inert until a
#: cmd entry point — or a test — configures a store directory)
WARM_POOL = WarmPool()
