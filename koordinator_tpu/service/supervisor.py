"""SolverSupervisor: the sidecar's failure domain gets an owner.

The koord-solver process used to be spawned by hand and supervised by
nobody: a crash left the control plane skipping rounds until a human
noticed (PAPER.md: Koordinator's node-agent/scheduler split is built to
survive component restarts — the supervisor is that property for the
solver boundary). This module owns the full child lifecycle:

- **Spawn.** ``spawn_fn`` produces a process-like handle (``poll()``/
  ``kill()``/``pid``). The default spawns ``python -m
  koordinator_tpu.cmd.solver --listen <spec>`` detached; tests and the
  chaos harness pass :class:`~koordinator_tpu.testing.chaos.
  InProcessSidecar` handles so a "restart" costs milliseconds, not a
  JAX import.
- **Probing.** Liveness = the child process is alive AND the solve
  address accepts (and holds) a connection — :func:`connection_probe`,
  shared with the failover layer so both sides agree on "healthy".
  ``probe_fn`` swaps in a debug-port ``/healthz`` probe
  (:func:`debug_port_probe`) when the sidecar serves one.
- **Restart.** A dead or hung child is respawned after a jittered
  exponential backoff (reset once a child probes healthy), counted in
  ``solver_supervisor_restarts_total``.
- **Restart-storm breaker.** More than ``threshold`` restarts inside
  ``window_s`` opens the breaker: the supervisor stops burning CPU on
  a child that dies on arrival (bad flag, poisoned cache, broken
  device) and re-probes with ONE half-open respawn per ``cooldown_s``.
  While open, the control plane rides the failover backend
  (service/failover.py) — degraded, but placing pods.

Every state transition is visible: :meth:`SolverSupervisor.status`
returns the machine-readable snapshot, and the gauges/counters land in
``metrics/components.py`` (SCHEDULER registry — the supervisor runs in
the control-plane process).
"""

from __future__ import annotations

import random
import socket
import threading
import time
from collections import deque
from typing import Callable, Dict, Optional

from koordinator_tpu.metrics.components import (
    SUPERVISOR_BREAKER_OPEN,
    SUPERVISOR_RESPAWN_WARM,
    SUPERVISOR_RESTARTS,
    SUPERVISOR_UP,
)
from koordinator_tpu.obs.trace import TRACER


def connection_probe(address, timeout_s: float = 1.0,
                     hold_s: float = 0.05) -> bool:
    """True iff ``address`` accepts a connection AND keeps it open.

    The hold matters: a proxy (or a half-dead server) can accept() from
    its listen backlog and immediately drop — connect success alone
    would report a corpse as healthy. The solve protocol never sends
    unsolicited bytes, so recv() returning ``b""`` inside ``hold_s``
    means the peer hung up; a timeout means the connection is being
    held — alive."""
    family = (socket.AF_UNIX if isinstance(address, str)
              else socket.AF_INET)
    sock = socket.socket(family, socket.SOCK_STREAM)
    try:
        sock.settimeout(timeout_s)
        sock.connect(address)
        sock.settimeout(hold_s)
        try:
            return sock.recv(1) != b""
        except socket.timeout:
            return True  # connection held open: listening and alive
    except OSError:
        return False
    finally:
        try:
            sock.close()
        except OSError:
            pass


def debug_port_probe(port: int, timeout_s: float = 1.0
                     ) -> Callable[[], bool]:
    """A ``probe_fn`` hitting the sidecar's ``--debug-port /healthz``
    (deeper than a connect probe: the HTTP thread answering proves the
    process is scheduling work, not just holding a listen socket)."""
    import urllib.request

    def probe() -> bool:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/healthz", timeout=timeout_s
            ) as resp:
                return resp.status == 200
        except OSError:
            return False

    return probe


def debug_port_warm_outcome(port: int, timeout_s: float = 1.0
                            ) -> Callable[[], Optional[bool]]:
    """A ``warm_outcome_fn`` reading the sidecar's warm-pool status off
    its debug mux (``/apis/v1/plugins/warm-pool``): True once the child
    reports restored/serving executables (probe it on the tight warm
    ready grace), False once it reports an active pool that restored
    nothing (cold — keep the cold-compile allowance), None while the
    child can't answer yet (undecided: stay generous)."""
    import json
    import urllib.request

    def outcome() -> Optional[bool]:
        try:
            with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/apis/v1/plugins/warm-pool",
                timeout=timeout_s,
            ) as resp:
                if resp.status != 200:
                    return None
                status = json.load(resp)
        except (OSError, ValueError):
            return None
        if not isinstance(status, dict) or not status.get("active"):
            return False  # no pool: every respawn is a cold respawn
        if status.get("executables"):
            return True
        report = status.get("last_restore")
        if isinstance(report, dict) and "restored" in report:
            return report["restored"] > 0
        return None  # boot restore still in flight

    return outcome


class RestartBreaker:
    """Restart-storm circuit breaker: ``threshold`` restarts inside
    ``window_s`` opens it; while open, :meth:`allow` grants ONE
    half-open respawn per ``cooldown_s`` (the same half-open shape as
    the kernel breaker in service/server.py). A child that stays
    healthy closes it via :meth:`record_healthy`."""

    def __init__(self, threshold: int = 5, window_s: float = 60.0,
                 cooldown_s: float = 120.0, clock=time.monotonic):
        self.threshold = threshold
        self.window_s = window_s
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self._restarts: deque = deque()
        self._tripped_at: Optional[float] = None
        self._last_probe_at: Optional[float] = None
        self._total_trips = 0

    def record_restart(self) -> bool:
        """Count one respawn; returns True when this one tripped."""
        with self._lock:
            now = self._clock()
            self._restarts.append(now)
            while self._restarts and self._restarts[0] < now - self.window_s:
                self._restarts.popleft()
            if (
                self._tripped_at is None
                and len(self._restarts) >= self.threshold
            ):
                self._tripped_at = now
                self._total_trips += 1
                return True
            return False

    def record_healthy(self) -> None:
        with self._lock:
            self._tripped_at = None
            self._last_probe_at = None
            self._restarts.clear()

    def allow(self) -> bool:
        with self._lock:
            if self._tripped_at is None:
                return True
            now = self._clock()
            since = now - (self._last_probe_at or self._tripped_at)
            if since >= self.cooldown_s:
                self._last_probe_at = now  # one half-open respawn
                return True
            return False

    def status(self) -> Dict[str, object]:
        with self._lock:
            return {
                "open": self._tripped_at is not None,
                "restarts_in_window": len(self._restarts),
                "threshold": self.threshold,
                "window_s": self.window_s,
                "cooldown_s": self.cooldown_s,
                "total_trips": self._total_trips,
            }


def _default_spawn(listen_spec: str, extra_argv=()):
    """Spawn a real koord-solver subprocess serving ``listen_spec``."""
    import subprocess
    import sys

    return subprocess.Popen(
        [sys.executable, "-m", "koordinator_tpu.cmd.solver",
         "--listen", listen_spec, *extra_argv],
        stdin=subprocess.DEVNULL,
    )


class SolverSupervisor:
    """Owns one sidecar child: spawn → probe → restart (with backoff
    and the storm breaker) → repeat, on a background monitor thread.

    ``address`` is the solve address probed for readiness/liveness
    (UDS path or (host, port)); ``listen_spec`` is the string form the
    default spawn passes to ``--listen`` (defaults to ``address`` when
    that is already a string). ``check_once()`` is the whole
    supervision step as a synchronous call — the monitor thread loops
    it, and deterministic tests drive it directly."""

    def __init__(self, address, listen_spec: Optional[str] = None,
                 spawn_fn: Optional[Callable[[], object]] = None,
                 probe_fn: Optional[Callable[[], bool]] = None,
                 extra_argv=(),
                 probe_interval_s: float = 1.0,
                 probe_timeout_s: float = 1.0,
                 probe_failure_threshold: int = 3,
                 ready_timeout_s: float = 120.0,
                 warm_ready_timeout_s: float = 15.0,
                 warm_outcome_fn: Optional[
                     Callable[[], Optional[bool]]] = None,
                 backoff_base_s: float = 0.25,
                 backoff_cap_s: float = 8.0,
                 breaker: Optional[RestartBreaker] = None,
                 clock=time.monotonic,
                 sleep=time.sleep,
                 rng: Optional[random.Random] = None):
        self.address = address
        if listen_spec is None and isinstance(address, str):
            listen_spec = address
        self.listen_spec = listen_spec
        if spawn_fn is None:
            if listen_spec is None:
                raise ValueError(
                    "spawn_fn is required for TCP addresses without a "
                    "listen_spec"
                )
            spawn_fn = lambda: _default_spawn(listen_spec, extra_argv)
        self._spawn_fn = spawn_fn
        self._probe_fn = probe_fn or (
            lambda: connection_probe(address, probe_timeout_s)
        )
        self.probe_interval_s = probe_interval_s
        self.probe_failure_threshold = probe_failure_threshold
        self.ready_timeout_s = ready_timeout_s
        #: probe-budget split (DESIGN §21): a child that WARM-restored
        #: from the AOT pool has no cold compile to hide behind — its
        #: ready grace is this tight budget, so a hung warm child is
        #: killed in seconds instead of the cold-compile allowance.
        #: ``warm_outcome_fn`` reports the current child's restore
        #: outcome (True warm / False cold / None undecided-yet —
        #: undecided keeps the generous grace); the default reads the
        #: spawn handle's ``warm_restored`` attribute
        #: (testing.chaos.InProcessSidecar carries it), and
        #: :func:`debug_port_warm_outcome` wires a real sidecar's
        #: debug mux. May do I/O: never called under the lock.
        self.warm_ready_timeout_s = warm_ready_timeout_s
        self._warm_outcome_fn = warm_outcome_fn
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self.breaker = breaker or RestartBreaker(clock=clock)
        self._clock = clock
        self._sleep = sleep
        self._rng = rng if rng is not None else random.Random()
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._proc: Optional[object] = None
        self.state = "new"
        self.restarts_total = 0
        self.consecutive_probe_failures = 0
        self.last_exit_code: Optional[int] = None
        self._backoff_attempt = 0
        #: when the current child was spawned, and whether it has EVER
        #: probed healthy since: a fresh child gets ``ready_timeout_s``
        #: of grace before failed probes count toward "hung" — a real
        #: koord-solver pays a multi-second JAX import on every spawn,
        #: and counting that as ill-health would kill each respawn
        #: before it ever served (an infanticide loop)
        self._spawned_at = self._clock()
        self._ready_since_spawn = False
        #: the current child's warm/cold restore outcome (None until
        #: resolved; reset on every spawn) and how many spawns resolved
        #: warm over this supervisor's lifetime
        self._respawn_warm: Optional[bool] = None
        self.respawns_warm_total = 0
        #: last time the EXTERNAL warm_outcome_fn was invoked while
        #: undecided — paces its I/O (an HTTP round trip against a
        #: booting child) at probe_interval_s even from _wait_ready's
        #: tight 50 ms poll loop
        self._warm_probe_at: Optional[float] = None

    def _resolve_warm_outcome(self) -> Optional[bool]:
        """The current child's warm/cold restore outcome, resolved at
        most once per spawn (lazily — a booting child may only know
        after its background restore lands). May do I/O
        (``warm_outcome_fn`` hits the child's debug mux), so this runs
        OUTSIDE the lock; the recorded outcome is guarded against a
        concurrent respawn swapping the handle."""
        with self._lock:
            known = self._respawn_warm
            proc = self._proc
        if known is not None or proc is None:
            return known
        if self._warm_outcome_fn is not None:
            now = self._clock()
            with self._lock:
                last = self._warm_probe_at
                if last is not None and \
                        now - last < self.probe_interval_s:
                    return None  # still undecided; don't hammer the mux
                self._warm_probe_at = now
            try:
                outcome = self._warm_outcome_fn()
            except Exception:
                outcome = None
        else:
            outcome = getattr(proc, "warm_restored", None)
        if outcome is None:
            return None
        recorded_warm = False
        with self._lock:
            if self._respawn_warm is None and self._proc is proc:
                self._respawn_warm = bool(outcome)
                if outcome:
                    self.respawns_warm_total += 1
                    recorded_warm = True
        if recorded_warm:
            SUPERVISOR_RESPAWN_WARM.inc()
            TRACER.instant("supervisor-respawn-warm", cat="supervisor")
        return bool(outcome)

    def _ready_grace_s(self, warm: Optional[bool]) -> float:
        """The ready grace the current child is entitled to: the tight
        warm budget once it is KNOWN to have warm-restored, the
        generous cold-compile allowance otherwise (cold or undecided —
        an undecided child must never be infanticided on the tight
        clock)."""
        return self.warm_ready_timeout_s if warm else self.ready_timeout_s

    # -- lifecycle -----------------------------------------------------------

    def start(self, wait_ready: bool = True,
              monitor: bool = True) -> "SolverSupervisor":
        """Spawn the child (optionally blocking until it probes ready)
        and start the background monitor. ``monitor=False`` skips the
        thread — deterministic tests then drive :meth:`check_once`
        themselves."""
        handle = self._spawn_fn()
        with self._lock:
            self._proc = handle
            self.state = "starting"
            self._spawned_at = self._clock()
            self._ready_since_spawn = False
            self._respawn_warm = None
            self._warm_probe_at = None
        if wait_ready and not self._wait_ready():
            raise TimeoutError(
                f"solver at {self.address!r} not ready within "
                f"{self.ready_timeout_s}s"
            )
        if monitor:
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="solver-supervisor"
            )
            self._thread.start()
        return self

    def stop(self) -> None:
        self._stop_event.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
        with self._lock:
            proc, self._proc = self._proc, None
            self.state = "stopped"
        if proc is not None:
            try:
                proc.kill()
            except OSError:
                pass
            wait = getattr(proc, "wait", None)
            if wait is not None:
                try:
                    # reap: a long-lived scheduler must not accumulate
                    # zombie children across supervisor lifecycles
                    wait(timeout=5)
                except Exception:
                    pass
        SUPERVISOR_UP.set(0)

    def _wait_ready(self) -> bool:
        t0 = self._clock()
        while True:
            if self._probe_fn():
                with self._lock:
                    self.state = "running"
                    self.consecutive_probe_failures = 0
                    self._backoff_attempt = 0
                    self._ready_since_spawn = True
                self.breaker.record_healthy()
                SUPERVISOR_UP.set(1)
                return True
            # the grace is re-evaluated per probe: a child that reports
            # a warm restore mid-wait drops to the tight budget
            warm = self._resolve_warm_outcome()
            if self._clock() - t0 >= self._ready_grace_s(warm):
                return False
            self._sleep(min(0.05, self.probe_interval_s))

    def _run(self) -> None:
        while not self._stop_event.is_set():
            try:
                self.check_once()
            except Exception:
                # the monitor must never die: a dead supervisor is the
                # exact failure mode this module exists to remove
                pass
            self._stop_event.wait(self.probe_interval_s)

    # -- one supervision step ------------------------------------------------

    def check_once(self) -> str:
        """Probe the child once and restart it if dead/hung. Returns the
        outcome ("running" | "probe-failed" | "restarted" |
        "breaker-open" | "stopped") — the monitor thread ignores it;
        deterministic tests assert on it."""
        with self._lock:
            if self.state == "stopped":
                return "stopped"
            proc = self._proc
        exit_code = None if proc is None else proc.poll()
        if proc is not None and exit_code is None:
            if self._probe_fn():
                with self._lock:
                    self.consecutive_probe_failures = 0
                    self._backoff_attempt = 0
                    self._ready_since_spawn = True
                    self.state = "running"
                self.breaker.record_healthy()
                SUPERVISOR_UP.set(1)
                SUPERVISOR_BREAKER_OPEN.set(0)
                return "running"
            # probe-budget split: resolved OUTSIDE the lock (the
            # outcome fn may hit the child's debug mux)
            warm = self._resolve_warm_outcome()
            with self._lock:
                # a fresh child that has never probed healthy is still
                # STARTING (cold JAX import), not hung — failed probes
                # only count once it served, or its ready grace
                # expired. A WARM-restored child gets only the tight
                # warm budget: it has no cold compile to hide behind,
                # so a hung warm respawn dies in seconds (DESIGN §21).
                if (
                    not self._ready_since_spawn
                    and self._clock() - self._spawned_at
                    < self._ready_grace_s(warm)
                ):
                    self.state = "starting"
                    return "starting"
                self.consecutive_probe_failures += 1
                hung = (self.consecutive_probe_failures
                        >= self.probe_failure_threshold)
                if not hung:
                    self.state = "probe-failed"
            SUPERVISOR_UP.set(0)
            if not hung:
                return "probe-failed"
            # alive but unreachable past the threshold: treat as hung —
            # kill, then fall through to the restart path
            try:
                proc.kill()
            except OSError:
                pass
            reason = "hung"
        else:
            reason = "crashed" if proc is not None else "down"
            SUPERVISOR_UP.set(0)
        return self._restart(reason, exit_code)

    def _restart(self, reason: str, exit_code: Optional[int]) -> str:
        from koordinator_tpu.service.client import jittered_backoff

        with self._lock:
            self.last_exit_code = exit_code
            if not self.breaker.allow():
                was_open = self.state == "breaker-open"
                self.state = "breaker-open"
                SUPERVISOR_BREAKER_OPEN.set(1)
                if not was_open:
                    # transition only — a refused respawn repeats every
                    # probe interval and must not spam the span ring
                    TRACER.instant("supervisor-breaker-open",
                                   cat="supervisor")
                return "breaker-open"
            attempt = self._backoff_attempt
            self._backoff_attempt += 1
            self.state = "restarting"
        delay = jittered_backoff(
            self.backoff_base_s, self.backoff_cap_s, attempt, self._rng
        )
        # the backoff wait must honor stop(): a plain sleep here could
        # outlive stop()'s bounded join and then spawn an ORPHAN child
        # nobody supervises or kills
        if self._stop_event.wait(delay):
            return "stopped"
        handle = self._spawn_fn()
        self.breaker.record_restart()
        with self._lock:
            self._proc = handle
            self.restarts_total += 1
            self.consecutive_probe_failures = 0
            self.state = "starting"
            self._spawned_at = self._clock()
            self._ready_since_spawn = False
            self._respawn_warm = None  # fresh child: outcome unknown
            self._warm_probe_at = None
        SUPERVISOR_RESTARTS.inc({"reason": reason})
        TRACER.instant("supervisor-restart", cat="supervisor",
                       args={"reason": reason})
        # from live state, not the trip transition: a half-open respawn
        # leaves the breaker OPEN and the gauge must keep saying so
        SUPERVISOR_BREAKER_OPEN.set(
            1 if self.breaker.status()["open"] else 0
        )
        return "restarted"

    # -- observability -------------------------------------------------------

    def status(self) -> Dict[str, object]:
        with self._lock:
            proc = self._proc
            out = {
                "state": self.state,
                "restarts_total": self.restarts_total,
                "consecutive_probe_failures":
                    self.consecutive_probe_failures,
                "last_exit_code": self.last_exit_code,
                "backoff_attempt": self._backoff_attempt,
                # probe-budget split (DESIGN §21): which grace the
                # current child is on, and how many spawns warm-restored
                "respawn_warm": self._respawn_warm,
                "respawns_warm_total": self.respawns_warm_total,
                "ready_grace_s": self._ready_grace_s(self._respawn_warm),
            }
        out["child_pid"] = getattr(proc, "pid", None)
        out["breaker"] = self.breaker.status()
        return out
