"""The control-plane ↔ solver service boundary.

SURVEY.md §5.8 / §7 north star: the Go control plane talks to the JAX
solver sidecar over an ordinary RPC carrying *batched* request/response
payloads that mirror the Score/Reserve plugin API — node/pod arrays in,
assignments out. Here the boundary is a length-prefixed binary protocol
(npz-packed arrays, language-neutral framing a C++/Go client can speak)
over a unix or TCP socket.
"""

from koordinator_tpu.service.codec import (  # noqa: F401
    CodecError,
    FrameTooLarge,
    SolveRequest,
    SolveResponse,
    TruncatedFrame,
    decode_request,
    decode_response,
    encode_request,
    encode_response,
    read_frame,
    write_frame,
)
from koordinator_tpu.service.admission import (  # noqa: F401
    AdmissionConfig,
    AdmissionGate,
    solve_coalesced,
)
from koordinator_tpu.service.tenancy import (  # noqa: F401
    DEFAULT_TENANT,
    TenantRegistry,
    solve_tenant_lanes,
    tenant_wire_value,
)
from koordinator_tpu.service.server import PlacementService  # noqa: F401
from koordinator_tpu.service.client import (  # noqa: F401
    PlacementClient,
    SolverDeadlineExceeded,
    SolverOverloaded,
    SolverShuttingDown,
    SolverUnavailable,
)
from koordinator_tpu.service.failover import FailoverSolver  # noqa: F401
from koordinator_tpu.service.supervisor import (  # noqa: F401
    RestartBreaker,
    SolverSupervisor,
    connection_probe,
)
