"""The solver sidecar: hosts the batched placement solve behind the wire
boundary.

One thread per connection, one solve per request frame. The solver keeps
its jit cache across requests (the first solve pays compilation; repeat
shapes are cached), which is the point of the sidecar: the control plane
restarts freely while the compiled solver stays warm.

Security: the UDS default inherits filesystem permissions. The TCP mode
is for trusted networks (the control-plane↔solver link of the north
star rides the cluster network); for anything beyond that, pass
``secret=`` — the first frame of every connection must then carry the
shared secret or the connection is dropped before any solve runs.
"""

from __future__ import annotations

import hmac
import socket
import socketserver
import threading
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.ops.binpack import (
    Extras,
    NodeState,
    NumaAux,
    PodBatch,
    ResvArrays,
    ScoreParams,
    SolverConfig,
    solve_batch,
)
from koordinator_tpu.ops.gang import GangState
from koordinator_tpu.ops.quota import QuotaState
from koordinator_tpu.service.codec import (
    SolveRequest,
    SolveResponse,
    decode_request,
    encode_response,
    read_frame,
    write_frame,
)

NODE_FIELDS = (
    "alloc", "used_req", "usage", "prod_usage", "est_extra", "prod_base",
    "metric_fresh", "schedulable",
)
POD_FIELDS = (
    "req", "est", "is_prod", "is_daemonset", "quota_id", "non_preemptible",
    "gang_id", "blocked", "has_numa_policy",
)

#: one jit cache for every connection (static config hashes per value)
_jit_solve = jax.jit(solve_batch, static_argnames=("config",))

#: kernel routing breaker, mirroring PlacementModel.use_pallas: None =
#: decide at first solve (single TPU chip => on), False after any
#: kernel error (visible via warning, never a silent slow path).
#: KTPU_SOLVER_PALLAS=1 forces it on (interpret mode off-TPU — tests),
#: =0 disables it.
_pallas_enabled: list = [None]


def _pallas_routing_on() -> bool:
    if _pallas_enabled[0] is None:
        import os

        forced = os.environ.get("KTPU_SOLVER_PALLAS")
        if forced is not None:
            _pallas_enabled[0] = forced != "0"
        else:
            devices = jax.devices()
            _pallas_enabled[0] = (
                len(devices) == 1 and devices[0].platform == "tpu"
            )
    return _pallas_enabled[0]


def _dispatch_solve(state, pods, params, config, quota, gang, extras,
                    resv, numa, resv_score_safe: bool, params_ok: bool):
    """Route eligible solves onto the pallas kernel (bit-identical,
    ~2-3x on TPU — the same routing the in-process PlacementModel does);
    everything else takes the scan with its AOT warm-start cache.
    ``resv_score_safe`` and ``params_ok`` are precomputed from the WIRE
    numpy arrays so the hot path pays no device->host sync."""
    from koordinator_tpu.ops.pallas_binpack import pallas_routing_ok

    kernel_ok = (
        _pallas_routing_on()
        and params_ok
        and pallas_routing_ok(
            state, pods, extras, resv, resv_score_safe, numa
        )
    )
    if kernel_ok:
        from koordinator_tpu.ops.pallas_binpack import pallas_solve_batch

        try:
            return pallas_solve_batch(
                state, pods, params, config, quota, gang, numa, resv,
                resv_score_checked=True,
            )
        except Exception as e:
            import warnings

            warnings.warn(
                f"solver sidecar pallas kernel disabled after error: "
                f"{type(e).__name__}: {e}",
                RuntimeWarning,
            )
            _pallas_enabled[0] = False
    return _cached_solve(
        state, pods, params, config, quota, gang, extras, resv, numa
    )

#: AOT warm-start: compiled executables persisted across process
#: restarts (utils/compilation_cache.ExecutableCache) — a respawned
#: sidecar's first solve deserializes instead of re-tracing+compiling
_loaded_execs: dict = {}


def _exec_cache():
    from koordinator_tpu.utils.compilation_cache import ExecutableCache

    return ExecutableCache()


def _program_key(config, *groups) -> str:
    """Program identity: every leaf's (path, shape, dtype) + the static
    config — the same key means the same compiled executable."""
    parts = [repr(tuple(config))]
    for group in groups:
        for path, leaf in jax.tree_util.tree_flatten_with_path(group)[0]:
            parts.append(
                f"{path}:{getattr(leaf, 'shape', ())}:"
                f"{getattr(leaf, 'dtype', type(leaf).__name__)}"
            )
    return "|".join(parts)


def _cached_solve(state, pods, params, config, quota, gang, extras, resv,
                  numa):
    if len(jax.devices()) != 1:
        # AOT executables pin device placement; the sidecar's production
        # shape is one chip per process — multi-device processes use the
        # plain jit cache
        return _jit_solve(state, pods, params, config, quota, gang,
                          extras, resv, numa)
    key = _program_key(
        config, state, pods, params, quota, gang, extras, resv, numa
    )
    entry = _loaded_execs.get(key)
    if entry is None:
        jit_fn = jax.jit(
            lambda s, p, pr, q, g, x, r, n: solve_batch(
                s, p, pr, config, q, g, x, r, n
            )
        )
        try:
            fn = _exec_cache().get_or_compile(
                key, jit_fn, state, pods, params, quota, gang, extras,
                resv, numa,
            )
        except Exception:
            fn = jit_fn  # AOT path is an optimization, never a gate
        entry = _loaded_execs[key] = (fn, jit_fn)
    fn, jit_fn = entry
    try:
        return fn(state, pods, params, quota, gang, extras, resv, numa)
    except Exception:
        # a stale/incompatible cached executable must not poison every
        # solve for this shape: fall back to the jit path and memoize it
        if fn is jit_fn:
            raise
        _loaded_execs[key] = (jit_fn, jit_fn)
        return jit_fn(state, pods, params, quota, gang, extras, resv, numa)


def _state_group(cls, group):
    """Reconstruct a NamedTuple-of-arrays feature state from its wire
    group (fields absent on the wire stay None)."""
    if group is None:
        return None
    return cls(**{
        f: (jnp.asarray(group[f]) if f in group else None)
        for f in cls._fields
    })


def _decode_config(group) -> SolverConfig:
    if group is None:
        return SolverConfig()
    defaults = SolverConfig()
    kwargs = {}
    for f in SolverConfig._fields:
        if f in group:
            default = getattr(defaults, f)
            kwargs[f] = type(default)(np.asarray(group[f]).item())
    return SolverConfig(**kwargs)


def solve_from_request(req: SolveRequest,
                       config: SolverConfig = SolverConfig()) -> SolveResponse:
    """Run one batched solve from wire arrays (the RPC handler body).

    The request's optional groups map 1:1 onto ``solve_batch``'s feature
    states; a wire config overrides the server default so the control
    plane's SolverConfig rides along."""
    try:
        state = NodeState(
            **{f: jnp.asarray(req.node[f]) for f in NODE_FIELDS},
            **{f: jnp.asarray(req.node[f])
               for f in ("numa_cap", "numa_free") if f in req.node},
        )
        pods = PodBatch.build(
            **{f: jnp.asarray(req.pods[f])
               for f in POD_FIELDS if f in req.pods}
        )
        params = ScoreParams(
            weights=jnp.asarray(req.params["weights"]),
            thresholds=jnp.asarray(req.params["thresholds"]),
            prod_thresholds=jnp.asarray(req.params["prod_thresholds"]),
        )
        if req.config is not None:
            config = _decode_config(req.config)
        # kernel-eligibility verdicts from the WIRE numpy arrays — free,
        # before anything lands on device; skipped entirely when routing
        # is off (CPU sidecar, tripped breaker)
        resv_score_safe = True
        params_ok = False
        if _pallas_routing_on():
            from koordinator_tpu.ops.pallas_binpack import (
                pallas_resv_score_safe,
                pallas_supported,
            )

            params_ok = pallas_supported(
                ScoreParams(**{k: req.params[k] for k in
                               ScoreParams._fields}), config
            )
            if req.resv is not None:
                resv_score_safe = pallas_resv_score_safe(
                    req.resv["node"], req.resv["free"], req.node["alloc"]
                )
        result = _dispatch_solve(
            state, pods, params, config,
            _state_group(QuotaState, req.quota),
            _state_group(GangState, req.gang),
            _state_group(Extras, req.extras),
            _state_group(ResvArrays, req.resv),
            _state_group(NumaAux, req.numa),
            resv_score_safe,
            params_ok,
        )
        opt = lambda a: None if a is None else np.asarray(a)
        return SolveResponse(
            assignments=np.asarray(result.assign),
            node_used_req=np.asarray(result.node_state.used_req),
            commit=np.asarray(result.commit),
            waiting=np.asarray(result.waiting),
            rejected=np.asarray(result.rejected),
            raw_assign=np.asarray(result.raw_assign),
            resv_vstar=opt(result.resv_vstar),
            resv_delta=opt(result.resv_delta),
        )
    except Exception as e:  # the boundary returns errors, never crashes
        return SolveResponse(
            assignments=np.empty(0, np.int32), error=f"{type(e).__name__}: {e}"
        )


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        stream = self.request.makefile("rwb")
        self.server.active_connections.add(self.request)
        try:
            secret = self.server.shared_secret
            if secret is not None:
                # secrets are short: cap the pre-auth frame so an
                # unauthenticated peer can't make us buffer MAX_FRAME
                try:
                    hello = read_frame(stream, max_frame=4096)
                except ValueError:
                    return
                if hello is None or not hmac.compare_digest(hello, secret):
                    return  # unauthenticated peer: drop before any solve
            while True:
                payload = read_frame(stream)
                if payload is None:
                    return
                try:
                    request = decode_request(payload)
                except Exception as e:
                    # malformed payload: report, keep the connection
                    response = SolveResponse(
                        assignments=np.empty(0, np.int32),
                        error=f"decode failed: {type(e).__name__}: {e}",
                    )
                else:
                    response = solve_from_request(
                        request, self.server.solver_config
                    )
                write_frame(stream, encode_response(response))
                stream.flush()
        finally:
            self.server.active_connections.discard(self.request)
            stream.close()


class PlacementService:
    """The sidecar server (UDS by default; TCP for cross-host —
    trusted-network-only unless ``secret`` is set)."""

    def __init__(self, address, config: SolverConfig = SolverConfig(),
                 secret: Optional[bytes] = None):
        self.address = address
        if isinstance(address, str):
            # a dead predecessor leaves its socket file behind; unlink it
            # iff nothing is accepting (the restart-in-place flow)
            import os

            if os.path.exists(address):
                probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                try:
                    probe.connect(address)
                except OSError:
                    os.unlink(address)
                else:
                    probe.close()
                    raise OSError(f"address in use: {address}")
            server_cls = type(
                "_UnixServer",
                (socketserver.ThreadingUnixStreamServer,),
                {"daemon_threads": True},
            )
        else:
            server_cls = type(
                "_TCPServer",
                (socketserver.ThreadingTCPServer,),
                {"daemon_threads": True, "allow_reuse_address": True},
            )
        self._server = server_cls(address, _Handler)
        self._server.solver_config = config
        self._server.shared_secret = secret
        self._server.active_connections = set()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        # sever live connections too — a stopped sidecar must look like
        # a dead process to its clients, not a half-open socket
        for conn in list(self._server.active_connections):
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
