"""The solver sidecar: hosts the batched placement solve behind the wire
boundary.

One thread per connection reads request frames, but solves no longer
run inline: every request passes through the admission gate
(service/admission.py) — a bounded, QoS-laned queue drained by a single
executor that coalesces same-base plain requests into one device
dispatch, enforces deadlines, and sheds best-effort work first under
overload (``PlacementService(admission=False)`` restores the inline
path). The solver keeps its jit cache across requests (the first solve
pays compilation; repeat shapes are cached), which is the point of the
sidecar: the control plane restarts freely while the compiled solver
stays warm.

Security: the UDS default inherits filesystem permissions. The TCP mode
is for trusted networks (the control-plane↔solver link of the north
star rides the cluster network); for anything beyond that, pass
``secret=`` — the first frame of every connection must then carry the
shared secret or the connection is dropped before any solve runs.
"""

from __future__ import annotations

import hmac
import socket
import socketserver
import threading
import time
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.ops.binpack import (
    STAGED_NODE_FIELDS,
    Extras,
    NodeState,
    NumaAux,
    PodBatch,
    ResvArrays,
    ScoreParams,
    SolverConfig,
    bucket_row_update,
    scatter_node_rows_copied,
    scatter_node_rows_donated,
    solve_batch,
)
from koordinator_tpu.obs.device import DEVICE_OBS
from koordinator_tpu.obs.trace import TRACER
from koordinator_tpu.ops.gang import GangState
from koordinator_tpu.ops.quota import QuotaState
from koordinator_tpu.service.admission import (
    LANE_NAMES,
    AdmissionConfig,
    AdmissionGate,
    request_lane,
)
from koordinator_tpu.state.workingset import WORKING_SET
from koordinator_tpu.service.codec import (
    SolveRequest,
    SolveResponse,
    decode_request,
    encode_response,
    read_frame,
    write_frame,
)

#: the wire NodeState columns — exactly the staged columns the delta
#: protocol patches, one source of truth so full and delta requests can
#: never drift
NODE_FIELDS = STAGED_NODE_FIELDS
POD_FIELDS = (
    "req", "est", "is_prod", "is_daemonset", "quota_id", "non_preemptible",
    "gang_id", "blocked", "has_numa_policy",
)

#: one jit cache for every connection (static config hashes per value);
#: the DEVICE_OBS wrapper adds compile telemetry (docs/DESIGN.md §17)
_jit_solve = DEVICE_OBS.jit("sidecar_solve_batch", jax.jit(
    solve_batch, static_argnames=("config",), donate_argnums=()
))
# AOT warm pool (docs/DESIGN.md §21): a supervisor-respawned sidecar
# restores this binding's executables at boot (cmd/solver.py), so its
# first solve deserializes instead of re-tracing + recompiling; the
# background persister keeps the store covering the hot signature set.
# Donation-free by construction (§19.2) — graftcheck pins the adopt.
from koordinator_tpu.service.warmpool import WARM_POOL  # noqa: E402

WARM_POOL.adopt(_jit_solve, solve_batch, config_argpos=3)

#: kernel routing availability, mirroring PlacementModel.use_pallas:
#: None = decide at first solve (single TPU chip => on).
#: KTPU_SOLVER_PALLAS=1 forces it on (interpret mode off-TPU — tests),
#: =0 disables it. Kernel FAILURES no longer flip this flag — they feed
#: the consecutive-failure breaker below.
_pallas_enabled: list = [None]


def _pallas_routing_on() -> bool:
    if _pallas_enabled[0] is None:
        import os

        forced = os.environ.get("KTPU_SOLVER_PALLAS")
        if forced is not None:
            _pallas_enabled[0] = forced != "0"
        else:
            devices = jax.devices()
            _pallas_enabled[0] = (
                len(devices) == 1 and devices[0].platform == "tpu"
            )
    return _pallas_enabled[0]


class KernelBreaker:
    """Kernel-routing circuit breaker (ADVICE r5 low #2).

    The old breaker permanently disabled kernel routing for the whole
    process on ANY single exception — one transient device hiccup cost
    2x throughput until restart, with a single RuntimeWarning as the
    only trace. This one:

    - trips only after ``threshold`` CONSECUTIVE kernel failures (a
      success resets the count);
    - excludes clearly request-specific errors — ``ValueError`` /
      ``TypeError`` are input/config validation, not kernel health, and
      never count (the request still falls back to the scan);
    - re-probes after ``cooldown_s``: one half-open solve is let
      through per cooldown window; success closes the breaker;
    - exposes its whole state via :meth:`status` (PlacementService
      surfaces it in the debug/status output).
    """

    REQUEST_SPECIFIC = (ValueError, TypeError)

    def __init__(self, threshold: int = 3, cooldown_s: float = 300.0,
                 clock=time.monotonic):
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self._clock = clock
        self._lock = threading.Lock()
        self.consecutive = 0
        self.tripped_at: Optional[float] = None
        self.last_probe_at: Optional[float] = None
        self.total_failures = 0
        self.total_trips = 0
        self.last_error: Optional[str] = None

    def allow(self) -> bool:
        """Whether a kernel solve may run now (half-open probes ride
        the cooldown clock)."""
        with self._lock:
            if self.tripped_at is None:
                return True
            now = self._clock()
            since = now - (self.last_probe_at or self.tripped_at)
            if since >= self.cooldown_s:
                self.last_probe_at = now  # one probe per cooldown window
                return True
            return False

    def record_success(self) -> None:
        with self._lock:
            was_tripped = self.tripped_at is not None
            self.consecutive = 0
            self.tripped_at = None
            self.last_probe_at = None
        if was_tripped:
            TRACER.instant("kernel-breaker-close", cat="breaker")

    def refund_probe(self) -> None:
        """A consumed half-open probe never actually tested kernel
        health (the solve failed on request-specific inputs): return
        the slot so the next eligible request can probe immediately."""
        with self._lock:
            if self.tripped_at is not None:
                self.last_probe_at = None

    def record_failure(self, exc: BaseException) -> bool:
        """Count a kernel-health failure; returns True when this one
        tripped (or re-armed) the breaker."""
        err = f"{type(exc).__name__}: {exc}"
        with self._lock:
            self.consecutive += 1
            self.total_failures += 1
            self.last_error = err
            if self.tripped_at is not None:
                # a failed half-open probe re-arms the cooldown
                self.last_probe_at = self._clock()
                tripped = True
            elif self.consecutive >= self.threshold:
                self.tripped_at = self._clock()
                self.total_trips += 1
                tripped = True
            else:
                tripped = False
        if tripped:
            TRACER.instant("kernel-breaker-open", cat="breaker",
                           args={"error": err})
        return tripped

    def status(self) -> Dict[str, object]:
        with self._lock:
            return {
                "tripped": self.tripped_at is not None,
                "consecutive_failures": self.consecutive,
                "failure_threshold": self.threshold,
                "cooldown_s": self.cooldown_s,
                "seconds_since_trip": (
                    None if self.tripped_at is None
                    else self._clock() - self.tripped_at
                ),
                "total_failures": self.total_failures,
                "total_trips": self.total_trips,
                "last_error": self.last_error,
            }


#: the process-wide breaker guarding kernel routing
_breaker = KernelBreaker()


def kernel_breaker_status() -> Dict[str, object]:
    """The sidecar's kernel-routing state for debug/status surfaces."""
    status = _breaker.status()
    status["routing_enabled"] = bool(_pallas_enabled[0]) \
        if _pallas_enabled[0] is not None else None
    return status


#: cached [Vp,Np] reservation→node one-hots for the kernel's credit
#: matmul, keyed by (node-table bytes, node count) — the sidecar serves
#: repeated solves against a static reservation table without
#: rebuilding the up-to-8MB operand per request
_resv_onehots: Dict = {}


def _resv_onehot_for(resv, n_nodes: int):
    if resv is None:
        return None
    node_np = np.asarray(resv.node, np.int32)
    key = (node_np.tobytes(), n_nodes)
    cached = _resv_onehots.get(key)
    if cached is None:
        from koordinator_tpu.ops.pallas_binpack import resv_node_onehot

        if len(_resv_onehots) > 8:  # drifting tables must not leak VMEM
            _resv_onehots.clear()
        cached = _resv_onehots[key] = resv_node_onehot(
            jnp.asarray(node_np), n_nodes
        )
    return cached


def _dispatch_solve(state, pods, params, config, quota, gang, extras,
                    resv, numa, resv_score_safe: bool, params_ok: bool):
    """Route eligible solves onto the pallas kernel (bit-identical,
    ~2-3x on TPU — the same routing the in-process PlacementModel does);
    everything else takes the scan with its AOT warm-start cache.
    ``resv_score_safe`` and ``params_ok`` are precomputed from the WIRE
    numpy arrays so the hot path pays no device->host sync."""
    from koordinator_tpu.ops.pallas_binpack import pallas_routing_ok

    # _breaker.allow() must come LAST: it consumes the half-open probe
    # slot when tripped, so a request that was never kernel-eligible
    # must not burn it (that would defer the real re-probe a cooldown)
    kernel_ok = (
        _pallas_routing_on()
        and params_ok
        and pallas_routing_ok(
            state, pods, extras, resv, resv_score_safe, numa
        )
        and _breaker.allow()
    )
    if kernel_ok:
        from koordinator_tpu.ops.pallas_binpack import pallas_solve_batch

        try:
            result = pallas_solve_batch(
                state, pods, params, config, quota, gang, numa, resv,
                resv_score_checked=True,
                resv_onehot=_resv_onehot_for(
                    resv, int(state.alloc.shape[0])
                ),
            )
            _breaker.record_success()
            return result
        except KernelBreaker.REQUEST_SPECIFIC as e:
            import warnings

            # bad inputs for the kernel, not kernel ill-health: this
            # request rides the scan, the breaker doesn't move — and if
            # it was a half-open probe, the slot is returned so a bad
            # request can't defer the real health re-probe
            _breaker.refund_probe()
            warnings.warn(
                f"solver sidecar kernel rejected a request (scan "
                f"fallback, breaker unchanged): {type(e).__name__}: {e}",
                RuntimeWarning,
            )
        except Exception as e:
            import warnings

            tripped = _breaker.record_failure(e)
            warnings.warn(
                f"solver sidecar pallas kernel failure"
                f"{' — breaker OPEN' if tripped else ''} "
                f"({_breaker.consecutive}/{_breaker.threshold}): "
                f"{type(e).__name__}: {e}",
                RuntimeWarning,
            )
    return _cached_solve(
        state, pods, params, config, quota, gang, extras, resv, numa
    )

def _cached_solve(state, pods, params, config, quota, gang, extras, resv,
                  numa):
    """The scan-path solve behind the warm pool: the adopted
    ``_jit_solve`` binding first consults the pool's restored AOT
    executables (a respawned sidecar's warm store — zero trace, zero
    compile, typed/quarantined load failures), and falls back to the
    ordinary jit cache on any miss. The bespoke per-program
    ``_loaded_execs`` machinery this replaces lives in
    service/warmpool.py now, shared with the promotion and failover
    warm paths (docs/DESIGN.md §21)."""
    return _jit_solve(state, pods, params, config, quota, gang,
                      extras, resv, numa)


def _state_group(cls, group):
    """Reconstruct a NamedTuple-of-arrays feature state from its wire
    group (fields absent on the wire stay None)."""
    if group is None:
        return None
    return cls(**{
        f: (jnp.asarray(group[f]) if f in group else None)
        for f in cls._fields
    })


def _decode_config(group) -> SolverConfig:
    if group is None:
        return SolverConfig()
    defaults = SolverConfig()
    kwargs = {}
    for f in SolverConfig._fields:
        if f in group:
            default = getattr(defaults, f)
            kwargs[f] = type(default)(np.asarray(group[f]).item())
    return SolverConfig(**kwargs)


class NodeStateCache:
    """Per-(connection, tenant) staged node state for the delta
    protocol.

    A full request carrying a ``node_delta`` ``epoch`` establishes the
    base: the server keeps BOTH the host arrays (kernel-eligibility
    predicates read them) and the staged device :class:`NodeState`.
    Subsequent delta requests patch both in place — the host rows by
    numpy assignment, the device arrays by the same donated row scatter
    the in-process staging cache uses — so steady-state solves through
    the sidecar never re-upload the [N,R] world either.

    The handler keys one cache per TENANT per connection (DESIGN §20):
    epoch fencing is a per-tenant chain, so a multi-tenant proxy
    multiplexing front-ends over one connection can never cross one
    tenant's delta into another tenant's base — a base/epoch mismatch
    stays a per-tenant ``delta-base-mismatch``, never silent
    cross-tenant state bleed.

    Every cache is a working-set resident (DESIGN §26): the staged
    device world it pins is priced against the HBM budget. Under
    pressure the manager demotes it host-pinned (``state`` dropped,
    ``host`` kept — the next delta restages through ``apply``) or cold
    (``host`` dropped too — the next delta gets the typed
    ``delta-base-mismatch`` and the client re-establishes, the
    protocol's existing self-heal). Both rungs rebuild the exact rows
    the resident carried, so placements stay bit-identical across the
    ladder by construction."""

    def __init__(self, tenant: str = "default", lane: str = "ls",
                 weight: float = 1.0):
        self.host: Optional[Dict[str, np.ndarray]] = None
        self.state: Optional[NodeState] = None
        self.epoch: Optional[int] = None
        self._ws_key = WORKING_SET.register_auto(
            "base", self, tenant=tenant, lane=lane, weight=weight
        )

    def device_bytes(self) -> int:
        """Live HBM held by the staged base (the working-set price)."""
        state = self.state
        if state is None:
            return 0
        return int(sum(
            getattr(state, f).nbytes for f in STAGED_NODE_FIELDS
            if getattr(state, f, None) is not None
        ))

    def demote_device(self) -> bool:
        """→ host-pinned: drop the device world, keep the host rows.

        Lock-free on purpose: ``host`` is authoritative and patched
        before every scatter, so a demotion racing ``apply`` at worst
        drops a generation the next delta restages bit-identically."""
        if self.state is None:
            return False
        self.state = None
        return True

    def demote_cold(self) -> bool:
        """→ cold: drop host too. The epoch survives so the client's
        next delta fails the base fence (typed mismatch → re-send)."""
        if self.host is None and self.state is None:
            return False
        self.host = None
        self.state = None
        return True

    def close(self) -> None:
        WORKING_SET.drop(self._ws_key)

    def establish(self, node_group, state: NodeState, epoch: int) -> None:
        self.host = {
            f: np.array(node_group[f], copy=True)
            for f in STAGED_NODE_FIELDS
        }
        self.state = state
        self.epoch = epoch
        WORKING_SET.touch(self._ws_key)

    def apply(self, delta) -> NodeState:
        if self.state is None:
            # host-pinned rung: the device world was demoted under
            # budget pressure but the host rows are authoritative —
            # restage them through the manager (admission headroom
            # first, typed alloc-failure ladder on failure) before
            # patching. Same rows the resident carried, so the solve
            # downstream is bit-identical to never-demoted.
            host = self.host
            self.state = WORKING_SET.run_staged(
                self._ws_key, "stage",
                lambda: NodeState(**{
                    f: jnp.asarray(host[f]) for f in STAGED_NODE_FIELDS
                }),
                estimate=int(sum(
                    host[f].nbytes for f in STAGED_NODE_FIELDS
                )),
            )
        idx = np.asarray(delta["idx"], np.int32)
        if idx.size:
            rows = {f: np.asarray(delta[f]) for f in STAGED_NODE_FIELDS}
            for f in STAGED_NODE_FIELDS:
                self.host[f][idx] = rows[f]
            sidx, srows = bucket_row_update(idx, rows)
            # single-device sidecars (the production shape) donate the
            # old generation; a MULTI-device process — the pool's lane
            # mesh, the 8-virtual-device test/bench harness — takes the
            # copying twin: jax 0.4.x donated jits in multi-device
            # processes mis-apply alias maps (DESIGN §19.2), and under
            # the pool's concurrency the donated replay corrupts the
            # heap outright. One [N,R]x6 row-buffer copy per tick is
            # the price of a staged world that is provably never
            # clobbered while a stacked lane dispatch reads it.
            scatter = (
                scatter_node_rows_donated
                if len(jax.devices()) == 1 else scatter_node_rows_copied
            )
            # the scatter allocates the new generation's row buffers —
            # the second alloc-failure boundary. Injected faults raise
            # BEFORE the callable runs (workingset contract), so the
            # post-demotion retry executes the scatter exactly once.
            base = self.state
            self.state = WORKING_SET.run_staged(
                self._ws_key, "scatter",
                lambda: scatter(base, jnp.asarray(sidx), srows),
            )
        self.epoch = int(np.asarray(delta["epoch"]).item())
        WORKING_SET.touch(self._ws_key)
        return self.state



def _trace_args(req: SolveRequest) -> Optional[Dict[str, int]]:
    """The wire trace context as span args ({} when the client sent
    none, None-safe against malformed scalars)."""
    group = req.trace
    if not group:
        return None
    out: Dict[str, int] = {}
    for key in ("round", "span"):
        if key in group:
            try:
                out[key] = int(np.asarray(group[key]).item())
            except (TypeError, ValueError):
                pass
    return out or None


def solve_from_request(req: SolveRequest,
                       config: SolverConfig = SolverConfig(),
                       node_cache: Optional[NodeStateCache] = None,
                       ) -> SolveResponse:
    """Run one batched solve from wire arrays (the RPC handler body).

    The request's optional groups map 1:1 onto ``solve_batch``'s feature
    states; a wire config overrides the server default so the control
    plane's SolverConfig rides along. ``node_cache`` (per connection)
    serves the delta protocol: requests without a ``node`` group patch
    the cached staged state instead of re-shipping it."""
    # the sidecar's "round" is a solve: an armed profiler window wraps
    # the next K requests (one flag read when no window is in play)
    DEVICE_OBS.on_round()
    t_solve = TRACER.now()
    try:
        delta = req.node_delta
        node_host = req.node
        if delta is not None and "idx" in delta:
            base = int(np.asarray(delta["base_epoch"]).item())
            # host-pinned bases (state demoted, host kept — DESIGN §26)
            # stay delta-eligible: apply() restages from host. Only a
            # COLD base (host gone too) forces the typed mismatch.
            if (
                node_cache is None
                or node_cache.host is None
                or node_cache.epoch != base
            ):
                have = None if node_cache is None else node_cache.epoch
                return SolveResponse(
                    assignments=np.empty(0, np.int32),
                    error=(
                        f"delta-base-mismatch: server holds epoch "
                        f"{have}, request expects {base}"
                    ),
                )
            state = node_cache.apply(delta)
            node_host = node_cache.host
        else:
            stage = lambda: NodeState(
                **{f: jnp.asarray(req.node[f]) for f in NODE_FIELDS},
                **{f: jnp.asarray(req.node[f])
                   for f in ("numa_cap", "numa_free") if f in req.node},
            )
            if node_cache is not None:
                # full staging is the first alloc boundary: admission
                # headroom (estimate) runs before the upload, and a
                # real/injected RESOURCE_EXHAUSTED rides the typed
                # demote→retry ladder instead of crashing the solve
                state = WORKING_SET.run_staged(
                    node_cache._ws_key, "stage", stage,
                    estimate=int(sum(
                        np.asarray(req.node[f]).nbytes for f in NODE_FIELDS
                    )),
                )
            else:
                state = stage()
            if (
                delta is not None
                and "epoch" in delta
                and node_cache is not None
                and "numa_cap" not in req.node  # numa rides full restage
            ):
                node_cache.establish(
                    req.node, state, int(np.asarray(delta["epoch"]).item())
                )
        pods = PodBatch.build(
            **{f: jnp.asarray(req.pods[f])
               for f in POD_FIELDS if f in req.pods}
        )
        params = ScoreParams(
            weights=jnp.asarray(req.params["weights"]),
            thresholds=jnp.asarray(req.params["thresholds"]),
            prod_thresholds=jnp.asarray(req.params["prod_thresholds"]),
        )
        if req.config is not None:
            config = _decode_config(req.config)
        # kernel-eligibility verdicts from the WIRE numpy arrays — free,
        # before anything lands on device; skipped entirely when routing
        # is off (CPU sidecar, tripped breaker)
        resv_score_safe = True
        params_ok = False
        if _pallas_routing_on():
            from koordinator_tpu.ops.pallas_binpack import (
                pallas_resv_score_safe,
                pallas_supported,
            )

            params_ok = pallas_supported(
                ScoreParams(**{k: req.params[k] for k in
                               ScoreParams._fields}), config
            )
            if req.resv is not None:
                resv_score_safe = pallas_resv_score_safe(
                    req.resv["node"], req.resv["free"], node_host["alloc"]
                )
        result = _dispatch_solve(
            state, pods, params, config,
            _state_group(QuotaState, req.quota),
            _state_group(GangState, req.gang),
            _state_group(Extras, req.extras),
            _state_group(ResvArrays, req.resv),
            _state_group(NumaAux, req.numa),
            resv_score_safe,
            params_ok,
        )
        # sidecar-side half of the round trip: tagged with the wire
        # trace context so it joins the scheduler's trace in Perfetto
        TRACER.emit("sidecar_solve", cat="sidecar", t0=t_solve,
                    args=_trace_args(req))
        opt = lambda a: None if a is None else np.asarray(a)
        return SolveResponse(
            assignments=np.asarray(result.assign),
            node_used_req=np.asarray(result.node_state.used_req),
            commit=np.asarray(result.commit),
            waiting=np.asarray(result.waiting),
            rejected=np.asarray(result.rejected),
            raw_assign=np.asarray(result.raw_assign),
            resv_vstar=opt(result.resv_vstar),
            resv_delta=opt(result.resv_delta),
        )
    except Exception as e:  # the boundary returns errors, never crashes
        return SolveResponse(
            assignments=np.empty(0, np.int32), error=f"{type(e).__name__}: {e}"
        )


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        from koordinator_tpu.service.tenancy import request_tenant

        stream = self.request.makefile("rwb")
        self.server.active_connections.add(self.request)
        #: per-connection delta bases, one per tenant — each tenant's
        #: epoch chain fences independently (DESIGN §20). LRU-bounded:
        #: tenant ids are wire-controlled, and every established base
        #: pins a full host+device world — without a cap one connection
        #: cycling ids could grow sidecar memory without bound. An
        #: evicted tenant's next delta gets the typed
        #: ``delta-base-mismatch`` and re-establishes (the protocol's
        #: existing self-heal), so the bound costs a re-send, never
        #: correctness.
        MAX_CONNECTION_TENANTS = 32
        node_caches: Dict[str, NodeStateCache] = {}
        try:
            secret = self.server.shared_secret
            if secret is not None:
                # secrets are short: cap the pre-auth frame so an
                # unauthenticated peer can't make us buffer MAX_FRAME
                try:
                    hello = read_frame(stream, max_frame=4096)
                except (ValueError, EOFError, OSError):
                    return
                if hello is None or not hmac.compare_digest(hello, secret):
                    return  # unauthenticated peer: drop before any solve
            while True:
                # a peer dying mid-request-frame (TruncatedFrame), an
                # insane length prefix (FrameTooLarge), or a reset
                # socket is a dead/hostile peer, not a server fault:
                # close quietly instead of leaking a handler traceback
                # through socketserver.handle_error
                try:
                    payload = read_frame(stream)
                except (EOFError, ValueError, OSError):
                    return
                if payload is None:
                    return
                entry = None
                try:
                    request = decode_request(payload)
                except Exception as e:
                    # malformed payload: report, keep the connection
                    response = SolveResponse(
                        assignments=np.empty(0, np.int32),
                        error=f"decode failed: {type(e).__name__}: {e}",
                    )
                else:
                    tenant = request_tenant(request)
                    node_cache = node_caches.pop(tenant, None)
                    if node_cache is None:
                        # the working-set ledger (DESIGN §26) wants the
                        # QoS lane and fair-share weight at admission
                        # time: BE tenants demote first, heavier
                        # tenants last
                        gate = self.server.admission_gate
                        node_cache = NodeStateCache(
                            tenant=tenant,
                            lane=LANE_NAMES[request_lane(request)],
                            weight=(1.0 if gate is None
                                    else gate.tenants.weight(tenant)),
                        )
                        while len(node_caches) >= MAX_CONNECTION_TENANTS:
                            # least-recently-used tenant's base evicted
                            # (dict order IS recency: hits re-insert)
                            node_caches.pop(
                                next(iter(node_caches))
                            ).close()
                    node_caches[tenant] = node_cache
                    gate = self.server.admission_gate
                    if gate is None:
                        response = solve_from_request(
                            request, self.server.solver_config, node_cache
                        )
                    else:
                        entry = gate.submit(
                            request, self.server.solver_config, node_cache
                        )
                        response = entry.wait()
                try:
                    try:
                        write_frame(stream, encode_response(response))
                        stream.flush()
                    except OSError:
                        return  # peer gone before the reply landed
                finally:
                    # count the delivery attempt even when the peer is
                    # gone, or stop()'s bounded delivery wait would
                    # burn its full timeout on a dead client
                    if entry is not None:
                        entry.delivered()
        finally:
            # drop the connection's working-set registrations — a gone
            # peer's staged bases must stop pinning the HBM budget
            for cache in node_caches.values():
                cache.close()
            self.server.active_connections.discard(self.request)
            stream.close()


def _preemption_status() -> dict:
    """Eviction-flow counters for :meth:`PlacementService.status`."""
    from koordinator_tpu.metrics.components import (
        DEFRAG_DRAINS,
        PREEMPT_VICTIMS,
        PREEMPTION_ATTEMPTS,
    )

    return {
        "attempts": PREEMPTION_ATTEMPTS.value(),
        "victims": {
            outcome: PREEMPT_VICTIMS.value({"outcome": outcome})
            for outcome in ("selected", "reprieved", "evicted")
        },
        "defrag_drains": DEFRAG_DRAINS.value(),
    }


class PlacementService:
    """The sidecar server (UDS by default; TCP for cross-host —
    trusted-network-only unless ``secret`` is set).

    ``admission`` selects the front-end: ``True`` (default) runs every
    solve through an :class:`AdmissionGate` with default sizing, an
    :class:`AdmissionConfig` customizes it, and ``False``/``None``
    restores the legacy inline per-connection solve (no queueing, no
    deadlines, no coalescing — the pre-gate behavior, kept as the
    bench baseline and an escape hatch).

    ``tenants`` is the multi-tenant pool's weight registry
    (service/tenancy.TenantRegistry, DESIGN §20): it parameterizes the
    gate's fair-share shedding and weighted-fair lane allocation.
    Omitted, every tenant (including the implicit ``default``) weighs
    1 — a single-tenant deployment behaves exactly as before."""

    def __init__(self, address, config: SolverConfig = SolverConfig(),
                 secret: Optional[bytes] = None,
                 admission=True, tenants=None):
        # embedders constructing the service directly (no cmd entry
        # point) keep the transparent AOT warm start the pre-pool
        # in-module executable cache gave them: configure from the
        # environment iff nothing configured the pool yet, restore
        # SEQUENTIALLY (a background restore racing the first client's
        # solve would cold-compile the very request a warm start
        # exists to answer), and persist newly observed signatures.
        # cmd/solver.py already did all of this — no-ops there; the
        # test suite's empty cache dir keeps the pool inert.
        WARM_POOL.ensure_configured()
        if WARM_POOL.active:
            WARM_POOL.restore(compile_missing=False)
            WARM_POOL.start_background()
        self.address = address
        if isinstance(address, str):
            # a dead predecessor leaves its socket file behind; unlink it
            # iff nothing is accepting (the restart-in-place flow)
            import os

            if os.path.exists(address):
                probe = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
                try:
                    probe.connect(address)
                except OSError:
                    os.unlink(address)
                else:
                    probe.close()
                    raise OSError(f"address in use: {address}")
            # a multi-tenant pool's front-ends (re)connect in gangs —
            # leader failover, rolling restarts — so the accept backlog
            # must hold a fleet, not the socketserver default of 5
            server_cls = type(
                "_UnixServer",
                (socketserver.ThreadingUnixStreamServer,),
                {"daemon_threads": True, "request_queue_size": 64},
            )
        else:
            server_cls = type(
                "_TCPServer",
                (socketserver.ThreadingTCPServer,),
                {"daemon_threads": True, "allow_reuse_address": True,
                 "request_queue_size": 64},
            )
        self._server = server_cls(address, _Handler)
        self._server.solver_config = config
        self._server.shared_secret = secret
        self._server.active_connections = set()
        if admission:
            gate_cfg = (admission if isinstance(admission, AdmissionConfig)
                        else AdmissionConfig())
            self.gate: Optional[AdmissionGate] = AdmissionGate(
                solve_from_request, gate_cfg,
                # a lone connected client never pays the coalesce window
                peer_count=self._server.active_connections.__len__,
                tenants=tenants,
            )
        else:
            self.gate = None
        self._server.admission_gate = self.gate
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def status(self) -> dict:
        """Debug/status snapshot: the address served, live connection
        count, the kernel-routing breaker state (so an operator can
        see WHY solves ride the scan instead of the kernel), and the
        admission gate's lane depths / coalesce ratio / shed counts —
        including the per-tenant rows (``admission.tenants``), so one
        tenant's overload is attributable from this one endpoint."""
        return {
            "address": self.address,
            "active_connections": len(self._server.active_connections),
            "kernel_breaker": kernel_breaker_status(),
            "admission": None if self.gate is None else self.gate.stats(),
            # padding-waste / live-buffer / compile counters beside the
            # lane-depth and coalesce stats (cached analyses only — a
            # status read never compiles)
            "device": DEVICE_OBS.status(),
            # the AOT warm pool's health (DESIGN §21): did this
            # sidecar's restart skip its compiles, and is the store
            # clean (hit/miss/quarantine counters, last typed error)
            "warm_pool": WARM_POOL.status(),
            # joint place+evict flow (DESIGN §24): victim selection /
            # reprieve / eviction counts and defrag drains, read from
            # the scheduler registry the control plane shares
            "preemption": _preemption_status(),
            # HBM working-set ledger (DESIGN §26): budget, per-rung
            # residency, demotion/restage/alloc-failure counters and
            # the top residents by bytes — pressure is attributable
            # from this one endpoint
            "workingset": WORKING_SET.status(),
        }

    def stop(self) -> None:
        # drain the admission gate FIRST: queued requests are answered
        # with a typed shutting-down error frame, and the bounded
        # delivery wait lets handler threads flush those frames before
        # connections are severed — in-flight clients see an error,
        # not a reset
        if self.gate is not None:
            self.gate.shutdown()
            self.gate.wait_delivered(timeout=2.0)
        self._server.shutdown()
        # sever live connections too — a stopped sidecar must look like
        # a dead process to its clients, not a half-open socket
        for conn in list(self._server.active_connections):
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
