"""The solver sidecar: hosts the batched placement solve behind the wire
boundary.

One thread per connection, one solve per request frame. The solver keeps
its jit cache across requests (the first solve pays compilation; repeat
shapes are cached), which is the point of the sidecar: the control plane
restarts freely while the compiled solver stays warm.

Security: the UDS default inherits filesystem permissions. The TCP mode
is for trusted networks (the control-plane↔solver link of the north
star rides the cluster network); for anything beyond that, pass
``secret=`` — the first frame of every connection must then carry the
shared secret or the connection is dropped before any solve runs.
"""

from __future__ import annotations

import hmac
import socket
import socketserver
import threading
from typing import Optional

import jax.numpy as jnp
import numpy as np

from koordinator_tpu.ops.binpack import (
    NodeState,
    PodBatch,
    ScoreParams,
    SolverConfig,
    solve_batch,
)
from koordinator_tpu.service.codec import (
    SolveRequest,
    SolveResponse,
    decode_request,
    encode_response,
    read_frame,
    write_frame,
)

NODE_FIELDS = (
    "alloc", "used_req", "usage", "prod_usage", "est_extra", "prod_base",
    "metric_fresh", "schedulable",
)
POD_FIELDS = (
    "req", "est", "is_prod", "is_daemonset", "quota_id", "non_preemptible",
    "gang_id", "blocked",
)


def solve_from_request(req: SolveRequest,
                       config: SolverConfig = SolverConfig()) -> SolveResponse:
    """Run one batched solve from wire arrays (the RPC handler body)."""
    try:
        state = NodeState(
            **{f: jnp.asarray(req.node[f]) for f in NODE_FIELDS}
        )
        pods = PodBatch.build(
            **{f: jnp.asarray(req.pods[f])
               for f in POD_FIELDS if f in req.pods}
        )
        params = ScoreParams(
            weights=jnp.asarray(req.params["weights"]),
            thresholds=jnp.asarray(req.params["thresholds"]),
            prod_thresholds=jnp.asarray(req.params["prod_thresholds"]),
        )
        result = solve_batch(state, pods, params, config)
        return SolveResponse(
            assignments=np.asarray(result.assign),
            node_used_req=np.asarray(result.node_state.used_req),
        )
    except Exception as e:  # the boundary returns errors, never crashes
        return SolveResponse(
            assignments=np.empty(0, np.int32), error=f"{type(e).__name__}: {e}"
        )


class _Handler(socketserver.BaseRequestHandler):
    def handle(self):
        stream = self.request.makefile("rwb")
        try:
            secret = self.server.shared_secret
            if secret is not None:
                hello = read_frame(stream)
                if hello is None or not hmac.compare_digest(hello, secret):
                    return  # unauthenticated peer: drop before any solve
            while True:
                payload = read_frame(stream)
                if payload is None:
                    return
                try:
                    request = decode_request(payload)
                except Exception as e:
                    # malformed payload: report, keep the connection
                    response = SolveResponse(
                        assignments=np.empty(0, np.int32),
                        error=f"decode failed: {type(e).__name__}: {e}",
                    )
                else:
                    response = solve_from_request(
                        request, self.server.solver_config
                    )
                write_frame(stream, encode_response(response))
                stream.flush()
        finally:
            stream.close()


class PlacementService:
    """The sidecar server (UDS by default; TCP for cross-host —
    trusted-network-only unless ``secret`` is set)."""

    def __init__(self, address, config: SolverConfig = SolverConfig(),
                 secret: Optional[bytes] = None):
        self.address = address
        if isinstance(address, str):
            server_cls = type(
                "_UnixServer",
                (socketserver.ThreadingUnixStreamServer,),
                {"daemon_threads": True},
            )
        else:
            server_cls = type(
                "_TCPServer",
                (socketserver.ThreadingTCPServer,),
                {"daemon_threads": True, "allow_reuse_address": True},
            )
        self._server = server_cls(address, _Handler)
        self._server.solver_config = config
        self._server.shared_secret = secret
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(
            target=self._server.serve_forever, daemon=True
        )
        self._thread.start()

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5)
