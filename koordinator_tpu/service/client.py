"""Control-plane client for the solver sidecar.

Speaks the framed npz protocol; ``solve_arrays`` takes the same host
arrays the in-process path lowers (state/cluster.py), so a control plane
swaps between in-process and sidecar solving without changing its
lowering. :class:`RemoteSolver` is the full PlacementModel backend
behind ``--placement-backend=sidecar`` (reference:
cmd/koord-scheduler/app/server.go:331-398 selects the plugin backend at
the same boundary): it serializes every feature state the batched solve
takes, reconnects transparently when the sidecar restarts, and raises
:class:`SolverUnavailable` when the sidecar stays down so the control
plane can skip the round and retry.
"""

from __future__ import annotations

import random
import socket
import time
from typing import Dict, Optional

import numpy as np


class SolverUnavailable(ConnectionError):
    """The sidecar cannot be reached (after reconnect attempts)."""


class SolverOverloaded(RuntimeError):
    """The sidecar's admission gate shed this request (typed
    ``overloaded`` response). The stream stays in sync — the request
    frame got a clean error frame — so the connection is reusable and
    the right reaction is jittered backoff, not reconnect churn."""


class SolverDeadlineExceeded(RuntimeError):
    """The request expired in the sidecar's admission queue (typed
    ``deadline-exceeded`` response) or its client-side budget ran out
    before a response arrived. Not retried: the caller's latency
    budget is gone by definition."""


class SolverShuttingDown(ConnectionError):
    """The sidecar is draining for shutdown (typed ``shutting-down``
    response): reconnect-and-retry territory, like a restart."""


from koordinator_tpu.obs.flight import FLIGHT
from koordinator_tpu.obs.trace import TRACER
from koordinator_tpu.service.codec import (
    CodecError,
    SolveRequest,
    SolveResponse,
    decode_response,
    encode_request,
    read_frame,
    write_frame,
)


def jittered_backoff(base_s: float, cap_s: float, attempt: int,
                     rng: random.Random) -> float:
    """The retry/restart delay both this module and the supervisor
    use: exponential from ``base_s`` capped at ``cap_s``, scaled by a
    uniform [0.5, 1.0) jitter so a fleet of clients (or supervisors)
    doesn't reconverge on the same instant."""
    return min(cap_s, base_s * (2 ** attempt)) * (0.5 + 0.5 * rng.random())


class PlacementClient:
    def __init__(self, address, timeout: float = 60.0, secret=None):
        if isinstance(address, str):
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        self._sock.connect(address)
        self._stream = self._sock.makefile("rwb")
        if secret is not None:
            # shared-secret hello frame (server.py handshake)
            write_frame(self._stream, secret)
            self._stream.flush()

    def solve(self, request: SolveRequest) -> SolveResponse:
        # serialization failures are LOCAL bugs, not transport faults:
        # encode outside the net below or a bad array would masquerade
        # as an unreachable solver and be retried forever
        encoded = encode_request(request)
        # a peer dying mid-frame (restart, SIGKILL, cut network) must
        # surface as the ONE typed transport error — SolverUnavailable —
        # never a bare EOFError/struct.error/BrokenPipeError the caller
        # has to pattern-match
        try:
            write_frame(self._stream, encoded)
            self._stream.flush()
            payload = read_frame(self._stream)
        except (EOFError, OSError, ValueError) as e:
            # ValueError covers FrameTooLarge: a garbage length prefix
            # means the stream is desynced — connection-level failure
            raise SolverUnavailable(
                f"solver connection failed mid-frame: "
                f"{type(e).__name__}: {e}"
            ) from e
        if payload is None:
            raise SolverUnavailable("solver closed the connection")
        response = decode_response(payload)
        if response.error:
            # admission-gate typed errors (the frame was read cleanly,
            # so the stream stays usable for overloaded retries)
            if response.error.startswith("overloaded"):
                raise SolverOverloaded(response.error)
            if response.error.startswith("deadline-exceeded"):
                raise SolverDeadlineExceeded(response.error)
            if response.error.startswith("shutting-down"):
                raise SolverShuttingDown(response.error)
            raise RuntimeError(f"solver error: {response.error}")
        return response

    def solve_arrays(
        self,
        node: Dict[str, np.ndarray],
        pods: Dict[str, np.ndarray],
        params: Dict[str, np.ndarray],
    ) -> SolveResponse:
        return self.solve(SolveRequest(node=node, pods=pods, params=params))

    def set_timeout(self, timeout: float) -> None:
        """Rebind the socket timeout (RemoteSolver caps each attempt's
        wait by the caller's remaining deadline budget)."""
        self._sock.settimeout(timeout)

    def close(self) -> None:
        try:
            # closing flushes buffered bytes: a dead peer turns that
            # into EPIPE, which must not mask the close itself
            self._stream.close()
        except OSError:
            pass
        self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _group(nt) -> Optional[Dict[str, np.ndarray]]:
    """NamedTuple-of-arrays -> wire group (None fields dropped)."""
    if nt is None:
        return None
    return {
        f: np.asarray(v)
        for f, v in zip(nt._fields, nt)
        if v is not None
    }


class RemoteSolver:
    """Routes ``PlacementModel``'s batched solves through the sidecar.

    The control plane keeps its lowering and epilogue; only the device
    solve crosses the wire. One persistent connection, re-established on
    failure (the sidecar restarting is the designed-for case: the
    control plane reconnects and the new sidecar re-warms its jit cache
    per shape bucket).
    """

    #: PlacementModel probes this before passing ``staging=`` — the
    #: sidecar protocol understands incremental node staging
    supports_staging_delta = True

    def __init__(self, address, secret: Optional[bytes] = None,
                 timeout: float = 120.0, retries: int = 1,
                 deadline_s: Optional[float] = None,
                 lane=None,
                 tenant: Optional[str] = None,
                 retry_total_s: float = 2.0,
                 backoff_base_s: float = 0.025,
                 backoff_cap_s: float = 0.5,
                 rng: Optional[random.Random] = None):
        """``deadline_s`` is the per-solve latency budget: propagated on
        the wire (the sidecar's admission gate sheds the request once
        the budget is spent instead of solving abandoned work), capping
        each attempt's socket wait, and bounding retries. ``lane`` is
        the QoS lane (``"system"``/``"ls"``/``"be"``, a lane code, or a
        :class:`~koordinator_tpu.apis.extension.QoSClass`). Transient
        failures — reconnects AND typed ``overloaded`` sheds — retry
        with jittered exponential backoff (``backoff_base_s`` doubling
        up to ``backoff_cap_s``) under a total-deadline cap of
        ``deadline_s`` (when set) or ``retry_total_s``: a slow or
        shedding sidecar can no longer hang a scheduler tick for the
        full socket timeout. ``retries`` keeps its old meaning as the
        guaranteed minimum retry count even when the budget is tiny.

        ``tenant`` names this front-end in a multi-tenant solver pool
        (DESIGN §20): it rides the wire ``admission`` group on every
        request, scoping the sidecar's coalescing, delta-base epoch
        chain, fair-share shedding, and metric labels to this tenant.
        None (the default) is the implicit single-tenant ``default``."""
        from koordinator_tpu.apis.extension import QoSClass
        from koordinator_tpu.service.admission import (
            LANE_BY_NAME,
            lane_for_qos,
        )

        self.address = address
        self.secret = secret
        self.timeout = timeout
        self.retries = retries
        self.deadline_s = deadline_s
        self.retry_total_s = retry_total_s
        self.backoff_base_s = backoff_base_s
        self.backoff_cap_s = backoff_cap_s
        self._rng = rng if rng is not None else random.Random()
        if lane is None:
            self.lane: Optional[int] = None
        elif isinstance(lane, QoSClass):
            self.lane = lane_for_qos(lane)
        elif isinstance(lane, str):
            self.lane = LANE_BY_NAME[lane]
        else:
            self.lane = int(lane)
        self.tenant = tenant
        self._client: Optional[PlacementClient] = None
        #: the staged-state epoch the CONNECTED sidecar holds as its
        #: delta base (None = none established / connection lost)
        self._server_epoch: Optional[int] = None
        #: which wire shape the last solve used — "full", "establish"
        #: or "delta" (observability/tests)
        self.last_request: Optional[str] = None

    def _connect(self, remaining: Optional[float] = None) -> PlacementClient:
        timeout = self.timeout
        if remaining is not None:
            # never park on the socket past the caller's budget
            timeout = max(0.05, min(self.timeout, remaining))
        if self._client is None:
            self._client = PlacementClient(
                self.address, timeout=timeout, secret=self.secret
            )
        else:
            self._client.set_timeout(timeout)
        return self._client

    def _drop(self) -> None:
        if self._client is not None:
            try:
                self._client.close()
            except OSError:
                pass
            self._client = None
        # a new connection lands on a handler with an empty delta base
        self._server_epoch = None

    def close(self) -> None:
        self._drop()

    def reset_base(self) -> None:
        """Drop the connection AND the delta base the connected sidecar
        was believed to hold: the next solve re-establishes with a full
        request. The failover layer calls this on flip-back so a solver
        that was restarted (or replaced) behind a proxy can never be
        handed a delta against a base it doesn't have."""
        self._drop()

    def solve_result(self, state, batch, params, config,
                     quota_state=None, gang_state=None, extras=None,
                     resv=None, numa=None, staging=None):
        """The ``solve_batch`` call over the wire; returns a
        ``SolveResult`` with host (numpy) arrays.

        ``staging`` is the model's ``(epoch, NodeStagingDelta)`` pair:
        when the connected sidecar already holds the delta's base epoch,
        only the dirty node rows cross the wire; otherwise the full node
        group is sent and establishes the base for subsequent ticks. A
        sidecar that lost the base (restart, connection churn) answers
        ``delta-base-mismatch`` and the solve transparently re-sends the
        full state on the same connection."""
        from koordinator_tpu.ops.binpack import SolveResult

        common = dict(
            pods=_group(batch),
            params=_group(params),
            quota=_group(quota_state),
            gang=_group(gang_state),
            extras=_group(extras),
            resv=_group(resv),
            numa=_group(numa),
            config={
                f: np.asarray(v) for f, v in zip(config._fields, config)
            },
        )

        # trace context rides the wire (codec v3): the sidecar tags its
        # queue/solve spans with this (round, span) pair so both halves
        # of the round trip land in ONE Perfetto trace
        span_id = TRACER.next_span_id() if TRACER.enabled else None
        trace_group = None
        if span_id is not None:
            trace_group = {
                "round": np.asarray(TRACER.round_id, np.int64),
                "span": np.asarray(span_id, np.int64),
            }

        def build_request(remaining: Optional[float]):
            admission = None
            if (remaining is not None or self.lane is not None
                    or self.tenant is not None):
                admission = {}
                if remaining is not None:
                    admission["deadline_s"] = np.asarray(
                        max(0.0, remaining), np.float64
                    )
                if self.lane is not None:
                    admission["lane"] = np.asarray(self.lane, np.int64)
                if self.tenant is not None:
                    from koordinator_tpu.service.tenancy import (
                        tenant_wire_value,
                    )

                    admission["tenant"] = tenant_wire_value(self.tenant)
            delta = staging[1] if staging is not None else None
            if (
                delta is not None
                and delta.base_epoch is not None
                and self._server_epoch == delta.base_epoch
            ):
                node_delta = {
                    "idx": np.asarray(
                        delta.idx if delta.idx is not None else [],
                        np.int32,
                    ),
                    "base_epoch": np.asarray(delta.base_epoch, np.int64),
                    "epoch": np.asarray(delta.epoch, np.int64),
                }
                node_delta.update(delta.rows or {})
                self.last_request = "delta"
                return SolveRequest(
                    node={}, node_delta=node_delta, admission=admission,
                    trace=trace_group, **common
                )
            node_delta = None
            if staging is not None:
                node_delta = {"epoch": np.asarray(staging[0], np.int64)}
            self.last_request = "establish" if node_delta else "full"
            return SolveRequest(
                node=_group(state), node_delta=node_delta,
                admission=admission, trace=trace_group, **common
            )

        # transient failures (reconnects, typed overloaded sheds) retry
        # with jittered exponential backoff under one total-deadline
        # cap: deadline_s when the caller set a budget, retry_total_s
        # otherwise. Per-ATTEMPT socket waits shrink to the remaining
        # budget only when deadline_s is set — that is opt-in by
        # design, because an un-deadlined first solve may legitimately
        # sit behind a multi-second cold-start compile
        start = time.monotonic()
        t_wire = TRACER.now()
        budget = (self.deadline_s if self.deadline_s is not None
                  else self.retry_total_s)
        last_error: Optional[Exception] = None
        attempt = 0
        mismatch_retry = True
        while True:
            remaining = None
            if self.deadline_s is not None:
                remaining = self.deadline_s - (time.monotonic() - start)
                if remaining <= 0:
                    FLIGHT.trigger(
                        "deadline-exceeded",
                        detail=f"client budget {self.deadline_s}s spent "
                               f"(last: "
                               f"{type(last_error).__name__ if last_error else None})",
                    )
                    raise SolverDeadlineExceeded(
                        f"deadline-exceeded: {self.deadline_s}s budget "
                        f"spent client-side (last: "
                        f"{type(last_error).__name__ if last_error else None})"
                    )
            try:
                response = self._connect(remaining).solve(
                    build_request(remaining)
                )
                break
            except SolverDeadlineExceeded as e:
                # the budget is gone by definition: retrying is pointless
                FLIGHT.trigger("deadline-exceeded", detail=str(e))
                raise
            except SolverOverloaded as e:
                # clean typed error frame — stream in sync, connection
                # kept; back off below instead of reconnect churn
                last_error = e
            except (ConnectionError, OSError, EOFError) as e:
                last_error = e
                self._drop()
            except CodecError as e:
                # garbage ON the wire (bit corruption, a desynced peer):
                # the framing held but the payload didn't decode. The
                # only safe recovery is a fresh connection — reconnect
                # and re-send, same as a dead peer
                last_error = e
                self._drop()
            except RuntimeError as e:
                if "delta-base-mismatch" in str(e) and mismatch_retry:
                    # the response was read cleanly — the stream is in
                    # sync; re-send the full state on this connection
                    mismatch_retry = False
                    self._server_epoch = None
                    continue
                self._drop()
                raise
            except Exception:
                # protocol-level failure (e.g. a solver error response):
                # the stream may be desynced — never reuse it, or a
                # retry would read the previous round's assignments
                self._drop()
                raise
            delay = jittered_backoff(
                self.backoff_base_s, self.backoff_cap_s, attempt,
                self._rng,
            )
            attempt += 1
            elapsed = time.monotonic() - start
            if attempt > self.retries and elapsed + delay >= budget:
                if isinstance(last_error, SolverOverloaded):
                    raise last_error
                raise SolverUnavailable(
                    f"placement sidecar at {self.address!r} unreachable: "
                    f"{type(last_error).__name__}: {last_error}"
                )
            time.sleep(delay)
        TRACER.emit("wire_solve", cat="wire", t0=t_wire, args={
            "span": span_id, "request": self.last_request,
            "retries": attempt,
        })
        if staging is not None:
            self._server_epoch = int(staging[0])
        new_state = state
        if response.node_used_req is not None:
            new_state = state._replace(used_req=response.node_used_req)
        return SolveResult(
            node_state=new_state,
            quota_state=None,
            resv_free=None,
            assign=response.assignments,
            commit=response.commit,
            waiting=response.waiting,
            rejected=response.rejected,
            raw_assign=response.raw_assign,
            resv_vstar=response.resv_vstar,
            resv_delta=response.resv_delta,
            numa_consumed=None,
        )
