"""Control-plane client for the solver sidecar.

Speaks the framed npz protocol; ``solve_arrays`` takes the same host
arrays the in-process path lowers (state/cluster.py), so a control plane
swaps between in-process and sidecar solving without changing its
lowering. :class:`RemoteSolver` is the full PlacementModel backend
behind ``--placement-backend=sidecar`` (reference:
cmd/koord-scheduler/app/server.go:331-398 selects the plugin backend at
the same boundary): it serializes every feature state the batched solve
takes, reconnects transparently when the sidecar restarts, and raises
:class:`SolverUnavailable` when the sidecar stays down so the control
plane can skip the round and retry.
"""

from __future__ import annotations

import socket
from typing import Dict, Optional

import numpy as np


class SolverUnavailable(ConnectionError):
    """The sidecar cannot be reached (after reconnect attempts)."""

from koordinator_tpu.service.codec import (
    SolveRequest,
    SolveResponse,
    decode_response,
    encode_request,
    read_frame,
    write_frame,
)


class PlacementClient:
    def __init__(self, address, timeout: float = 60.0, secret=None):
        if isinstance(address, str):
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        self._sock.connect(address)
        self._stream = self._sock.makefile("rwb")
        if secret is not None:
            # shared-secret hello frame (server.py handshake)
            write_frame(self._stream, secret)
            self._stream.flush()

    def solve(self, request: SolveRequest) -> SolveResponse:
        write_frame(self._stream, encode_request(request))
        self._stream.flush()
        payload = read_frame(self._stream)
        if payload is None:
            raise ConnectionError("solver closed the connection")
        response = decode_response(payload)
        if response.error:
            raise RuntimeError(f"solver error: {response.error}")
        return response

    def solve_arrays(
        self,
        node: Dict[str, np.ndarray],
        pods: Dict[str, np.ndarray],
        params: Dict[str, np.ndarray],
    ) -> SolveResponse:
        return self.solve(SolveRequest(node=node, pods=pods, params=params))

    def close(self) -> None:
        self._stream.close()
        self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


def _group(nt) -> Optional[Dict[str, np.ndarray]]:
    """NamedTuple-of-arrays -> wire group (None fields dropped)."""
    if nt is None:
        return None
    return {
        f: np.asarray(v)
        for f, v in zip(nt._fields, nt)
        if v is not None
    }


class RemoteSolver:
    """Routes ``PlacementModel``'s batched solves through the sidecar.

    The control plane keeps its lowering and epilogue; only the device
    solve crosses the wire. One persistent connection, re-established on
    failure (the sidecar restarting is the designed-for case: the
    control plane reconnects and the new sidecar re-warms its jit cache
    per shape bucket).
    """

    #: PlacementModel probes this before passing ``staging=`` — the
    #: sidecar protocol understands incremental node staging
    supports_staging_delta = True

    def __init__(self, address, secret: Optional[bytes] = None,
                 timeout: float = 120.0, retries: int = 1):
        self.address = address
        self.secret = secret
        self.timeout = timeout
        self.retries = retries
        self._client: Optional[PlacementClient] = None
        #: the staged-state epoch the CONNECTED sidecar holds as its
        #: delta base (None = none established / connection lost)
        self._server_epoch: Optional[int] = None
        #: which wire shape the last solve used — "full", "establish"
        #: or "delta" (observability/tests)
        self.last_request: Optional[str] = None

    def _connect(self) -> PlacementClient:
        if self._client is None:
            self._client = PlacementClient(
                self.address, timeout=self.timeout, secret=self.secret
            )
        return self._client

    def _drop(self) -> None:
        if self._client is not None:
            try:
                self._client.close()
            except OSError:
                pass
            self._client = None
        # a new connection lands on a handler with an empty delta base
        self._server_epoch = None

    def close(self) -> None:
        self._drop()

    def solve_result(self, state, batch, params, config,
                     quota_state=None, gang_state=None, extras=None,
                     resv=None, numa=None, staging=None):
        """The ``solve_batch`` call over the wire; returns a
        ``SolveResult`` with host (numpy) arrays.

        ``staging`` is the model's ``(epoch, NodeStagingDelta)`` pair:
        when the connected sidecar already holds the delta's base epoch,
        only the dirty node rows cross the wire; otherwise the full node
        group is sent and establishes the base for subsequent ticks. A
        sidecar that lost the base (restart, connection churn) answers
        ``delta-base-mismatch`` and the solve transparently re-sends the
        full state on the same connection."""
        from koordinator_tpu.ops.binpack import SolveResult

        common = dict(
            pods=_group(batch),
            params=_group(params),
            quota=_group(quota_state),
            gang=_group(gang_state),
            extras=_group(extras),
            resv=_group(resv),
            numa=_group(numa),
            config={
                f: np.asarray(v) for f, v in zip(config._fields, config)
            },
        )

        def build_request():
            delta = staging[1] if staging is not None else None
            if (
                delta is not None
                and delta.base_epoch is not None
                and self._server_epoch == delta.base_epoch
            ):
                node_delta = {
                    "idx": np.asarray(
                        delta.idx if delta.idx is not None else [],
                        np.int32,
                    ),
                    "base_epoch": np.asarray(delta.base_epoch, np.int64),
                    "epoch": np.asarray(delta.epoch, np.int64),
                }
                node_delta.update(delta.rows or {})
                self.last_request = "delta"
                return SolveRequest(
                    node={}, node_delta=node_delta, **common
                )
            node_delta = None
            if staging is not None:
                node_delta = {"epoch": np.asarray(staging[0], np.int64)}
            self.last_request = "establish" if node_delta else "full"
            return SolveRequest(
                node=_group(state), node_delta=node_delta, **common
            )

        last_error: Optional[Exception] = None
        conn_attempts = 0
        mismatch_retry = True
        while conn_attempts <= self.retries:
            try:
                response = self._connect().solve(build_request())
                break
            except (ConnectionError, OSError, EOFError) as e:
                last_error = e
                conn_attempts += 1
                self._drop()
            except RuntimeError as e:
                if "delta-base-mismatch" in str(e) and mismatch_retry:
                    # the response was read cleanly — the stream is in
                    # sync; re-send the full state on this connection
                    mismatch_retry = False
                    self._server_epoch = None
                    continue
                self._drop()
                raise
            except Exception:
                # protocol-level failure (e.g. a solver error response):
                # the stream may be desynced — never reuse it, or a
                # retry would read the previous round's assignments
                self._drop()
                raise
        else:
            raise SolverUnavailable(
                f"placement sidecar at {self.address!r} unreachable: "
                f"{type(last_error).__name__}: {last_error}"
            )
        if staging is not None:
            self._server_epoch = int(staging[0])
        new_state = state
        if response.node_used_req is not None:
            new_state = state._replace(used_req=response.node_used_req)
        return SolveResult(
            node_state=new_state,
            quota_state=None,
            resv_free=None,
            assign=response.assignments,
            commit=response.commit,
            waiting=response.waiting,
            rejected=response.rejected,
            raw_assign=response.raw_assign,
            resv_vstar=response.resv_vstar,
            resv_delta=response.resv_delta,
            numa_consumed=None,
        )
