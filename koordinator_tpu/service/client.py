"""Control-plane client for the solver sidecar.

Speaks the framed npz protocol; ``solve_arrays`` takes the same host
arrays the in-process path lowers (state/cluster.py), so a control plane
swaps between in-process and sidecar solving without changing its
lowering.
"""

from __future__ import annotations

import socket
from typing import Dict, Optional

import numpy as np

from koordinator_tpu.service.codec import (
    SolveRequest,
    SolveResponse,
    decode_response,
    encode_request,
    read_frame,
    write_frame,
)


class PlacementClient:
    def __init__(self, address, timeout: float = 60.0, secret=None):
        if isinstance(address, str):
            self._sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        else:
            self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.settimeout(timeout)
        self._sock.connect(address)
        self._stream = self._sock.makefile("rwb")
        if secret is not None:
            # shared-secret hello frame (server.py handshake)
            write_frame(self._stream, secret)
            self._stream.flush()

    def solve(self, request: SolveRequest) -> SolveResponse:
        write_frame(self._stream, encode_request(request))
        self._stream.flush()
        payload = read_frame(self._stream)
        if payload is None:
            raise ConnectionError("solver closed the connection")
        response = decode_response(payload)
        if response.error:
            raise RuntimeError(f"solver error: {response.error}")
        return response

    def solve_arrays(
        self,
        node: Dict[str, np.ndarray],
        pods: Dict[str, np.ndarray],
        params: Dict[str, np.ndarray],
    ) -> SolveResponse:
        return self.solve(SolveRequest(node=node, pods=pods, params=params))

    def close(self) -> None:
        self._stream.close()
        self._sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
