"""Multi-tenant solver pool: K scheduler front-ends sharing ONE warm
solver sidecar, their per-tick solves batched ACROSS tenants as lanes
of a single device dispatch (docs/DESIGN.md §20).

The north star is a fleet of clusters, not one scheduler per TPU pod:
every tenant (one scheduler front-end / cluster) keeps its own staged
world, its own wire-delta epoch chain, its own QoS budgets and deadline
accounting — and the device still sees ONE program. The two measured
halves this fuses:

- the admission gate's same-base coalescing (DESIGN §12): K callers'
  pod bursts against one shared base become vmap lanes of one dispatch;
- the pod-lane axis of the 2-D mesh (DESIGN §19): K INDEPENDENT
  stacked solves, collective-free, bit-identical per lane.

Here each lane carries its OWN node base: per-tenant worlds are staged
into one shared *node bucket* (the repo's quarter-step pow2 family,
:func:`parallel.mesh.pow2_quarter_bucket`) and stacked ``[K, N*, ...]``;
pod batches stack ``[K, P*, ...]`` in their own bucket; the lane count
pads to a pow2 multiple of the lane-shard count. A dispatch therefore
compiles per (lane bucket, node bucket, pod bucket, config) — tenants
joining or leaving INSIDE a bucket reuse the warm program with zero XLA
recompiles, which is what makes a pool of drifting front-ends cheap.

**Isolation contract** (the hard requirement, tested in
tests/test_tenancy.py):

- *No cross-tenant base merge*: the gate's coalesce fingerprint
  (service/admission.coalesce_key) feeds the tenant identity, so two
  tenants shipping byte-identical worlds still never merge into one
  base — they ride separate lanes with separate bases.
- *Bit-identical placements*: the solver is integer arithmetic end to
  end, so every tenant's lane output equals that tenant solving solo —
  placements, per-lane node accounting, tie-breaks included.
- *Per-tenant epochs*: the delta protocol's base/epoch fencing stays
  per tenant-connection (service/server.py keys its NodeStateCache by
  tenant); delta requests never join a cross-tenant batch.
- *Per-tenant overload accounting*: shed/deadline counts are kept and
  exported per tenant, and the gate's shed policy respects the
  weighted fair share (:func:`fair_share`): one tenant's burst may only
  evict queued work of tenants OVER their share (or its own).
- *Weighted-fair lane budget*: when more same-bucket requests wait than
  one dispatch can carry, :func:`allocate_fair_lanes` splits the lane
  budget across tenants in proportion to their weights.
"""

from __future__ import annotations

import hashlib
import re
import threading
from typing import Callable, Dict, List, Mapping, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from koordinator_tpu.obs.device import DEVICE_OBS
from koordinator_tpu.ops.binpack import (
    STAGED_NODE_FIELDS,
    NodeState,
    PodBatch,
    ScoreParams,
    SolverConfig,
    solve_batch,
)
from koordinator_tpu.parallel.mesh import pow2_quarter_bucket
from koordinator_tpu.service.codec import SolveRequest, SolveResponse

#: requests without a wire tenant belong to the default tenant — a
#: single-tenant deployment never has to name itself
DEFAULT_TENANT = "default"

#: wire tenant ids are bounded (they become metric label values and
#: dict keys); longer ids are truncated, undecodable ones fall back
MAX_TENANT_LEN = 64

#: the tenant-id alphabet: ids become Prometheus label VALUES and the
#: exposition format does no escaping in this repo's registry — a
#: quote or newline in a wire-supplied id would corrupt the whole
#: /metrics scrape for every tenant. Anything outside this set maps
#: to ``_``.
_TENANT_CHAR_RE = re.compile(r"[^A-Za-z0-9._\-]")

#: per-tenant accounting (gate stats rows, depth gauges) is keyed by
#: the WIRE tenant id: ids past this distinct-count cap fold into
#: :data:`OVERFLOW_TENANT` so a client cycling unique tenant strings
#: (or fleets embedding per-restart suffixes) cannot grow the sidecar's
#: memory, metric cardinality, or per-submit gauge publishing without
#: bound. Registered (weighted) tenants are always tracked.
MAX_TRACKED_TENANTS = 256
OVERFLOW_TENANT = "_overflow"


def request_tenant(req: SolveRequest) -> str:
    """The request's tenant identity from the wire ``admission`` group
    (``tenant``: utf-8 bytes as a uint8 array, like the response error
    string). Absent / undecodable means :data:`DEFAULT_TENANT` — v2
    single-tenant clients ride through unchanged. Ids are truncated to
    :data:`MAX_TENANT_LEN` and sanitized to the label-safe alphabet
    (``[A-Za-z0-9._-]``): tenant names come off the WIRE and land in
    metric label values, so a hostile id must never be able to break
    the metrics exposition."""
    adm = req.admission
    if not adm or "tenant" not in adm:
        return DEFAULT_TENANT
    try:
        raw = bytes(np.asarray(adm["tenant"], np.uint8))
        name = raw.decode("utf-8")
    except (TypeError, ValueError, UnicodeDecodeError):
        return DEFAULT_TENANT
    name = _TENANT_CHAR_RE.sub("_", name[:MAX_TENANT_LEN])
    return name if name else DEFAULT_TENANT


def tenant_wire_value(tenant: str) -> np.ndarray:
    """Encode a tenant id for the ``admission`` group (client half)."""
    return np.frombuffer(tenant.encode("utf-8"), dtype=np.uint8)


# -- shape buckets -----------------------------------------------------------

def node_bucket(n: int) -> int:
    """The staged node-axis bucket for a tenant world of ``n`` nodes."""
    return pow2_quarter_bucket(n, floor=8)


def pod_bucket(p: int) -> int:
    """The stacked pod-axis bucket for a lane of ``p`` pending pods."""
    return pow2_quarter_bucket(p, floor=8)


def lane_bucket(k: int, shards: int = 1) -> int:
    """The lane-count bucket for ``k`` tenant lanes over ``shards``
    lane shards: a power of two of per-shard lanes (so a tenant joining
    or leaving inside the bucket reuses the compiled program) times the
    shard count (so a ``NamedSharding`` split stays equal-width).
    Padding lanes are hard-blocked duplicates — they place nothing."""
    shards = max(1, shards)
    per_shard = -(-max(1, k) // shards)
    return shards * (1 << (per_shard - 1).bit_length())


#: params every solve must carry (ScoreParams schema)
_PARAM_FIELDS = ScoreParams._fields
#: pod columns PodBatch.build accepts; the first four are required
_POD_FIELDS = PodBatch._fields
_POD_REQUIRED = ("req", "est", "is_prod", "is_daemonset")


def plain_request(req: SolveRequest) -> bool:
    """Whether ``req`` is a PLAIN full-state solve — no feature groups,
    no delta protocol, full staged node schema, a complete pod/params
    schema. Plain requests batch directly on their wire world's shape
    (:func:`shape_bucket_key`); pure DELTA requests batch through
    :func:`delta_shape_key` against their staged base; feature-group
    solves always ride the solo path."""
    if (
        req.quota is not None
        or req.gang is not None
        or req.extras is not None
        or req.resv is not None
        or req.numa is not None
        or req.node_delta is not None
    ):
        return False
    if set(req.node) != set(STAGED_NODE_FIELDS):
        return False  # NUMA inventories (or a short node group) ride solo
    if not set(_POD_REQUIRED) <= set(req.pods):
        return False
    if not set(req.pods) <= set(_POD_FIELDS):
        return False
    if not set(_PARAM_FIELDS) <= set(req.params):
        return False
    return True


def _schema_digest(req: SolveRequest, node_cols: Mapping[str, np.ndarray],
                   n_nodes: int) -> bytes:
    """The shared shape fingerprint body: node/pod/param schema with
    bucketed leading axes + static config VALUES. ``node_cols`` is the
    world's column source — the wire ``node`` group for a plain
    request, the per-tenant cache's host arrays for a delta request —
    so both batching tiers hash the same shape domain and a plain lane
    and a delta lane can share one program."""
    h = hashlib.blake2b(digest_size=16)

    def feed_schema(tag: str, a: np.ndarray, lead_bucket=None) -> None:
        h.update(tag.encode())
        h.update(str(a.dtype).encode())
        if lead_bucket is None:
            h.update(repr(a.shape).encode())
        else:
            h.update(repr((lead_bucket,) + a.shape[1:]).encode())

    p = int(np.asarray(req.pods["req"]).shape[0])
    nb, pb = node_bucket(n_nodes), pod_bucket(p)
    for f in STAGED_NODE_FIELDS:
        feed_schema("n." + f, np.asarray(node_cols[f]), lead_bucket=nb)
    for f in sorted(req.pods):
        feed_schema("p." + f, np.asarray(req.pods[f]), lead_bucket=pb)
    for f in sorted(req.params):
        feed_schema("s." + f, np.asarray(req.params[f]))
    if req.config is not None:
        # config is a STATIC jit argument: values, not just schema
        for f in sorted(req.config):
            a = np.asarray(req.config[f])
            feed_schema("c." + f, a)
            h.update(np.ascontiguousarray(a).tobytes())
    return h.digest()


def shape_bucket_key(req: SolveRequest) -> Optional[bytes]:
    """SHAPE-level fingerprint for cross-tenant lane batching, or None
    when the request cannot batch.

    Two requests with equal keys stage into the same (node bucket, pod
    bucket) and run under the same static config — they can be lanes of
    ONE compiled program even though every byte of their node/pod/param
    DATA differs (that is the point: separate tenants, separate
    worlds). Unlike :func:`~koordinator_tpu.service.admission.
    coalesce_key` no array data is hashed — only dtypes, trailing dims,
    the bucketed leading axes, and the static config values (a static
    jit argument must be equal across lanes)."""
    if not plain_request(req):
        return None
    n = int(np.asarray(req.node["alloc"]).shape[0])
    return _schema_digest(req, req.node, n)


def delta_request(req: SolveRequest) -> bool:
    """Whether ``req`` is a pure DELTA solve — a ``node_delta`` row
    patch against the per-tenant-connection cached base, no feature
    groups, no inline node group, complete pod/params schema. The
    steady-state serving shape: these may lane-batch across tenants
    exactly like plain requests, each lane solving against its own
    (patched) staged world."""
    if (
        req.quota is not None
        or req.gang is not None
        or req.extras is not None
        or req.resv is not None
        or req.numa is not None
    ):
        return False
    if req.node:
        return False  # an inline node group means full/establish, not delta
    delta = req.node_delta
    if not delta or "idx" not in delta or "base_epoch" not in delta:
        return False
    # a malformed patch (missing row columns, row/idx length mismatch)
    # must ride SOLO: batched, its staging failure would poison every
    # co-batched tenant's response with a typed internal error —
    # exactly the cross-tenant blast radius the pool promises away
    if "epoch" not in delta:
        return False
    idx = np.asarray(delta["idx"])
    if idx.ndim != 1:
        return False
    for f in STAGED_NODE_FIELDS:
        if f not in delta:
            return False
        if np.asarray(delta[f]).shape[:1] != idx.shape[:1]:
            return False
    if not set(_POD_REQUIRED) <= set(req.pods):
        return False
    if not set(req.pods) <= set(_POD_FIELDS):
        return False
    if not set(_PARAM_FIELDS) <= set(req.params):
        return False
    return True


def delta_shape_key(req: SolveRequest, node_cache) -> Optional[bytes]:
    """The shape-bucket key of a DELTA request against its tenant's
    established base, or None when it must ride solo (not a pure delta,
    no base, or a base/epoch mismatch — the solo path then answers the
    typed ``delta-base-mismatch``).

    Safe to compute at submit time: per-tenant-connection caches are
    mutated only by the gate's single executor, and a connection has at
    most one request in flight, so the cache's epoch cannot change
    between this check and the dispatch that applies the patch."""
    if not delta_request(req):
        return None
    # state is deliberately NOT required: a host-pinned base (device
    # world demoted under HBM pressure, DESIGN §26) still lane-batches
    # — apply() restages it from host before the stack. Only a cold
    # base (host gone) rides solo for the typed mismatch.
    if (
        node_cache is None
        or node_cache.host is None
        or node_cache.epoch is None
    ):
        return None
    try:
        base = int(np.asarray(req.node_delta["base_epoch"]).item())
    except (TypeError, ValueError):
        return None
    if node_cache.epoch != base:
        return None  # mismatch: the solo path owns the typed error
    n = int(node_cache.host["alloc"].shape[0])
    return _schema_digest(req, node_cache.host, n)


# -- weighted-fair arbitration ----------------------------------------------

def fair_share(capacity: int, weights: Mapping[str, float]) -> Dict[str, int]:
    """Per-tenant queue fair share: ``capacity`` split in proportion to
    the tenants' weights (floor 1 — a registered tenant can always hold
    at least one entry). Tenants at or under their share are protected
    from cross-tenant eviction (the gate's shed policy)."""
    total = sum(max(0.0, w) for w in weights.values()) or 1.0
    return {
        t: max(1, int(capacity * max(0.0, w) / total))
        for t, w in weights.items()
    }


def allocate_fair_lanes(
    candidates: Mapping[str, Sequence],
    weight_of: Callable[[str], float],
    budget: int,
    room: int,
    pods_of: Callable[[object], int],
    preloaded: Optional[Mapping[str, int]] = None,
) -> List[object]:
    """Split one dispatch window's lane budget across contending
    tenants in proportion to their weights.

    ``candidates`` maps tenant -> its queued same-bucket entries in
    FIFO order; ``budget`` is how many lanes remain, ``room`` how many
    summed pod rows (the gate's ``max_coalesced_pods`` bound);
    ``preloaded`` counts lanes already granted (the claimed batch
    head). Classic weighted round-robin: repeatedly grant the next
    entry of the tenant with the smallest granted/weight ratio —
    deterministic (ties break on tenant name), starvation-free (every
    positive-weight tenant with work gets a lane before any tenant gets
    its k+1st at equal weights)."""
    cursors = {t: 0 for t in candidates}
    granted: Dict[str, int] = dict(preloaded or {})
    out: List[object] = []
    while budget > 0:
        best: Optional[str] = None
        best_ratio = None
        for t in sorted(candidates):
            q = candidates[t]
            i = cursors[t]
            while i < len(q) and pods_of(q[i]) > room:
                i += 1  # oversized for the remaining room: skip, keep FIFO
            cursors[t] = i
            if i >= len(q):
                continue
            w = max(1e-9, weight_of(t))
            ratio = granted.get(t, 0) / w
            if best is None or ratio < best_ratio:
                best, best_ratio = t, ratio
        if best is None:
            break
        entry = candidates[best][cursors[best]]
        cursors[best] += 1
        granted[best] = granted.get(best, 0) + 1
        room -= pods_of(entry)
        budget -= 1
        out.append(entry)
    return out


class TenantRegistry:
    """Weights and membership for the pool's tenants.

    Read-mostly: the gate consults it on every submit/dispatch, an
    operator (or test) registers tenants up front. Unregistered tenants
    are implicitly weight-1 — the pool serves unknown front-ends with
    equal fairness rather than refusing them."""

    DEFAULT_WEIGHT = 1.0

    def __init__(self, weights: Optional[Mapping[str, float]] = None):
        #: guards _weights (graftcheck lock map)
        self._lock = threading.Lock()
        self._weights: Dict[str, float] = dict(weights or {})

    def set_weight(self, tenant: str, weight: float) -> None:
        if weight <= 0:
            raise ValueError(f"tenant weight must be > 0, got {weight}")
        with self._lock:
            self._weights[tenant] = float(weight)

    def weight(self, tenant: str) -> float:
        with self._lock:
            return self._weights.get(tenant, self.DEFAULT_WEIGHT)

    def weights_for(self, tenants) -> Dict[str, float]:
        """The weight map over ``tenants`` (implicit members included)."""
        with self._lock:
            return {
                t: self._weights.get(t, self.DEFAULT_WEIGHT)
                for t in tenants
            }

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._weights)


# -- the cross-tenant lane dispatch -----------------------------------------

def _vmapped_tenant_solve(states, pods, params, config):
    """K tenants' independent solves — each lane against its OWN base
    and params — as ONE XLA program (assignments only: the [K,N,R]
    state carry is dead weight on the serving path, PR 15's
    ``want_state=False`` measurement)."""
    return jax.vmap(
        lambda s, p, pr: solve_batch(s, p, pr, config).assign
    )(states, pods, params)


def _vmapped_tenant_solve_full(states, pods, params, config):
    """The ``want_state=True`` twin: per-lane mutated ``used_req``
    rides back too (isolation property tests compare it to solo)."""
    def body(s, p, pr):
        r = solve_batch(s, p, pr, config)
        return r.node_state.used_req, r.assign

    return jax.vmap(body)(states, pods, params)


#: one jitted multi-base program per (lane bucket, node bucket, pod
#: bucket, config) shape, shared by every gate in the process
_jit_tenant = DEVICE_OBS.jit("tenant_pool_solve", jax.jit(
    _vmapped_tenant_solve, static_argnames=("config",), donate_argnums=()
))
_jit_tenant_full = DEVICE_OBS.jit("tenant_pool_solve_full", jax.jit(
    _vmapped_tenant_solve_full, static_argnames=("config",),
    donate_argnums=(),
))
# Tenant-aware warm manifest (ROADMAP 2b, docs/DESIGN.md §21/§22): the
# pool program's aval signature IS the tenant shape-bucket signature —
# [K*, N*, ...] bucketed axes, zero tenant data — so a persisted lane
# dispatch warms EVERY tenant that lands in the bucket, including a
# tenant the sidecar has never seen: its first solve restores the
# stacked program from the store instead of cold-compiling. Adoption is
# legal because the bindings never donate (§19.2; graftcheck pins every
# adopt site against its binding).
from koordinator_tpu.service.warmpool import WARM_POOL  # noqa: E402

WARM_POOL.adopt(_jit_tenant, _vmapped_tenant_solve, config_argpos=3)
WARM_POOL.adopt(_jit_tenant_full, _vmapped_tenant_solve_full,
                config_argpos=3)

#: lane-sharded dispatch (multi-device hosts): mesh + solver built
#: lazily, cached per (config, want_state) — the virtual 8-device test
#: mesh and a real pod slice both route here
_lane_mesh = [False]  # False = unprobed, None = single device
_tenant_solvers: Dict = {}
_tenant_solver_lock = threading.Lock()


def _sharded_tenant_solver(config: SolverConfig, want_state: bool):
    """The lane-sharded dispatch for this process's devices, or None on
    a single-device host (the plain vmap jit is the right program
    there)."""
    from koordinator_tpu.parallel.mesh import (
        make_mesh2d,
        shard_tenant_solver,
    )

    with _tenant_solver_lock:
        if _lane_mesh[0] is False:
            devices = jax.devices()
            _lane_mesh[0] = (
                make_mesh2d(devices, node_shards=1,
                            pod_shards=len(devices))
                if len(devices) > 1 else None
            )
        mesh = _lane_mesh[0]
        if mesh is None:
            return None
        key = (tuple(config), want_state)
        solver = _tenant_solvers.get(key)
        if solver is None:
            solver = _tenant_solvers[key] = shard_tenant_solver(
                mesh, config, want_state=want_state
            )
        return solver


def lane_shard_count() -> int:
    """How many ways the pool's lane dispatch shards (1 = plain vmap)."""
    if _lane_mesh[0] is False:
        _sharded_tenant_solver(SolverConfig(), False)
    mesh = _lane_mesh[0]
    if mesh is None:
        return 1
    from koordinator_tpu.parallel.mesh import POD_AXIS, mesh_axis_size

    return mesh_axis_size(mesh, POD_AXIS)


def _stage_lanes(pairs, shards: int):
    """Stack K lanes into the bucketed batch: ``(states [K*,N*,...],
    pods [K*,P*,...], params [K*,...], counts, node_counts, K*)``.

    ``pairs`` is ``[(request, lane_state_or_None), ...]`` — a lane's
    world comes from its wire ``node`` group (plain request,
    host-staged here) or from its tenant's already-staged device
    :class:`NodeState` (delta request, patched by the caller). Every
    axis rides its bucket — node and pod padding rows are inert
    (unschedulable zero nodes / hard-blocked pods, the same
    "permanently empty node" rows the sharded staging appends), lane
    padding duplicates the last lane fully blocked — so outputs trim
    back to exactly what each tenant solving solo would have
    produced."""
    head = pairs[0][0]
    node_counts = [
        int(state.alloc.shape[0]) if state is not None
        else int(np.asarray(r.node["alloc"]).shape[0])
        for r, state in pairs
    ]
    counts = [
        int(np.asarray(r.pods["req"]).shape[0]) for r, _ in pairs
    ]
    nb = node_bucket(max(node_counts))
    pb = pod_bucket(max(counts))
    k = len(pairs)
    kb = lane_bucket(k, shards)
    DEVICE_OBS.note_padding("tenant_nodes", sum(node_counts), k * nb)
    DEVICE_OBS.note_padding("tenant_pods", sum(counts), k * pb)
    DEVICE_OBS.note_padding("tenant_lanes", k, kb)

    def pad_rows(a: np.ndarray, target: int) -> np.ndarray:
        if a.shape[0] == target:
            return a
        pw = [(0, target - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
        return np.pad(a, pw)  # 0 == False: inert padding on every column

    def pad_rows_dev(a, target: int):
        if a is None or a.shape[0] == target:
            return a
        pw = [(0, target - a.shape[0])] + [(0, 0)] * (a.ndim - 1)
        return jnp.pad(a, pw)  # device pad, no host round-trip

    lane_states: List[NodeState] = []
    for r, state in pairs:
        if state is not None:
            lane_states.append(NodeState(
                *(pad_rows_dev(x, nb) for x in state)
            ))
        else:
            lane_states.append(NodeState(
                **{f: pad_rows(np.asarray(r.node[f]), nb)
                   for f in STAGED_NODE_FIELDS}
            ))
    lane_states += [lane_states[-1]] * (kb - k)  # dup lanes, blocked below
    from koordinator_tpu.parallel.mesh import stack_node_states

    states = stack_node_states(lane_states)

    pod_fields = sorted(set(head.pods) - {"blocked"})
    pod_cols: Dict[str, np.ndarray] = {}
    for f in pod_fields:
        lanes = [pad_rows(np.asarray(r.pods[f]), pb) for r, _ in pairs]
        lanes += [lanes[-1]] * (kb - k)
        pod_cols[f] = np.stack(lanes)
    blocked = np.ones((kb, pb), bool)
    for i, ((r, _), p) in enumerate(zip(pairs, counts)):
        blocked[i, :p] = (
            np.asarray(r.pods["blocked"]) if "blocked" in r.pods else False
        )
    pods = PodBatch.build(
        blocked=jnp.asarray(blocked),
        **{f: jnp.asarray(v) for f, v in pod_cols.items()},
    )

    param_cols = {}
    for f in ScoreParams._fields:
        lanes = [np.asarray(r.params[f]) for r, _ in pairs]
        lanes += [lanes[-1]] * (kb - k)
        param_cols[f] = np.stack(lanes)
    params = ScoreParams(
        **{f: jnp.asarray(v) for f, v in param_cols.items()}
    )
    return states, pods, params, counts, node_counts, kb


def _solve_lanes(pairs, config, want_state: bool) -> List[SolveResponse]:
    """Dispatch ``pairs`` as lanes, splitting into per-shard-sized
    chunks on multi-device hosts (ROADMAP 2a / ISSUE 12): one stacked
    dispatch carrying more lanes than the mesh has lane shards builds
    an oversized multi-lane-per-device program — at 16+ tenants on the
    8-virtual-device child the XLA:CPU mapping pressure segfaulted the
    process outright. Chunks of exactly ``lane_shard_count()`` lanes
    keep every dispatch at one lane per device; chunks within a shape
    bucket reuse one compiled program, and per-lane results are
    bit-identical either way (lanes are independent by construction)."""
    shards = lane_shard_count()
    if shards > 1 and len(pairs) > shards:
        out: List[SolveResponse] = []
        for i in range(0, len(pairs), shards):
            out.extend(
                _solve_lane_chunk(pairs[i:i + shards], config,
                                  want_state, shards)
            )
        return out
    return _solve_lane_chunk(pairs, config, want_state, shards)


def _solve_lane_chunk(pairs, config, want_state: bool,
                      shards: int) -> List[SolveResponse]:
    head = pairs[0][0]
    if config is None:
        config = SolverConfig()
    if head.config is not None:
        from koordinator_tpu.service.server import _decode_config

        config = _decode_config(head.config)
    states, pods, params, counts, node_counts, kb = _stage_lanes(
        pairs, shards
    )
    solver = _sharded_tenant_solver(config, want_state) if shards > 1 \
        else None
    if solver is not None:
        used_req, assign = solver(states, pods, params)
    elif want_state:
        # config rides POSITIONALLY (jax resolves static_argnames to
        # argnums): the warm pool's serve() answers only kwarg-free
        # calls, and this is exactly the call shape its AOT programs
        # were persisted under
        used_req, assign = _jit_tenant_full(states, pods, params, config)
    else:
        used_req = None
        assign = _jit_tenant(states, pods, params, config)
    assign_all = np.asarray(assign)
    used_all = None if used_req is None else np.asarray(used_req)
    out: List[SolveResponse] = []
    for i, (p, n) in enumerate(zip(counts, node_counts)):
        a = np.asarray(assign_all[i, :p], np.int32)
        out.append(SolveResponse(
            assignments=a,
            node_used_req=(
                None if used_all is None else used_all[i, :n]
            ),
            # plain/delta solves: commit IS "placed"; gang/quota/numa
            # requests never reach this path (the batchability
            # predicates gate it)
            commit=a >= 0,
            waiting=np.zeros(p, bool),
            rejected=np.zeros(p, bool),
            raw_assign=a,
        ))
    return out


def solve_tenant_lanes(
    requests: Sequence[SolveRequest],
    config: Optional[SolverConfig] = SolverConfig(),
    want_state: bool = False,
) -> List[SolveResponse]:
    """Solve K tenants' plain requests — separate worlds, separate
    params, one shape bucket — as lanes of ONE device dispatch and
    split the results back per tenant.

    The program is the multi-base vmap (``assignments`` only by
    default); on a multi-device host the lane axis shards over the
    ``pods`` mesh axis (:func:`parallel.mesh.shard_tenant_solver`), so
    K front-ends' ticks cost one sharded dispatch. Each returned
    :class:`SolveResponse` is bit-identical to what
    ``solve_from_request`` would have produced for that tenant alone
    (``want_state=True`` additionally carries the per-lane
    ``node_used_req`` — the isolation property tests compare it; the
    serving path leaves it off, the [K,N,R] carry being measured dead
    weight)."""
    return _solve_lanes(
        [(r, None) for r in requests], config, want_state
    )


def solve_entry_lanes(entries, config=None) -> List[SolveResponse]:
    """The gate's lane dispatch over admission entries: each entry is a
    plain request (world staged from the wire) or a DELTA request
    (its tenant-connection's staged base patched on the executor
    thread, then joined to the stack ON DEVICE). This is the
    steady-state serving shape of the pool: K tenants' per-tick deltas
    cost kilobytes of wire and one fused dispatch, while every lane
    stays bit-identical to that tenant solving solo.

    (A fused scatter-inside-solve variant was measured and REJECTED:
    adopting the patched [K,N,...] stack back into the caches leaves
    mesh-resident bases whose every later eager staging op pays an
    8-device sync barrier — the pool round ballooned 2-5x. The
    two-step shape below — per-cache scatter, then stack — keeps the
    staged bases single-device and measured fastest.)"""
    from koordinator_tpu.state.workingset import WorkingSetExhausted

    pairs = []
    slots: List[Optional[int]] = []  # entry index -> pairs index
    failed: Dict[int, SolveResponse] = {}
    for i, e in enumerate(entries):
        req = e.request
        state = None
        if delta_request(req):
            # epoch eligibility was established at submit time and
            # cannot have changed (only this executor thread mutates
            # caches, one request per connection in flight) — but the
            # RESIDENCY can have: an earlier entry's restage in this
            # very loop may have demoted this base under HBM pressure
            # (DESIGN §26). A cold base or an exhausted budget costs
            # THIS entry a typed error, never the co-batched lanes.
            cache = e.node_cache
            if cache is None or cache.host is None:
                failed[i] = SolveResponse(
                    assignments=np.empty(0, np.int32),
                    error=(
                        "delta-base-mismatch: base demoted cold under "
                        "memory pressure, re-establish"
                    ),
                )
                slots.append(None)
                continue
            try:
                state = cache.apply(req.node_delta)
            except WorkingSetExhausted as exc:
                failed[i] = SolveResponse(
                    assignments=np.empty(0, np.int32),
                    error=f"overloaded: {exc}",
                )
                slots.append(None)
                continue
        slots.append(len(pairs))
        pairs.append((req, state))
    solved = _solve_lanes(pairs, config, want_state=False) if pairs else []
    return [
        failed[i] if slot is None else solved[slot]
        for i, slot in enumerate(slots)
    ]
