"""FailoverSolver: degraded-mode placement through a solver outage.

``--placement-backend=sidecar`` made the sidecar the only road to the
device — and a single failure domain: ``run_loop`` skipped the round
whenever :class:`~koordinator_tpu.service.client.RemoteSolver` gave up.
This backend wraps the remote solver with the failure-domain state
machine (docs/DESIGN.md §13):

- **Per-solve fallback.** A remote attempt that ends in
  ``SolverUnavailable`` / ``SolverDeadlineExceeded`` is answered by the
  lazily-compiled in-process solve INSTEAD of raising — the control
  plane places pods on every tick, outage or not. The local path is the
  same ``solve_batch`` program the sidecar runs (integer arithmetic end
  to end, DESIGN.md §2), so placements are bit-identical; the first
  local solve pays the cold compile, by design.
- **Degraded mode.** ``failure_threshold`` CONSECUTIVE remote failures
  flip the machine to degraded: solves stop paying the remote timeout
  at all and go straight to the local path, while each solve spends one
  cheap liveness probe (:func:`~koordinator_tpu.service.supervisor.
  connection_probe`) on the sidecar address.
- **Hysteresis.** ``recovery_probes`` CONSECUTIVE healthy probes flip
  back — one blip during recovery resets the count, so a flapping
  sidecar cannot bounce the backend between modes.
- **Epoch reset on flip-back.** Recovery calls
  ``RemoteSolver.reset_base()`` (the restarted sidecar holds no delta
  base) and the ``on_flip_back`` hook — the control plane wires it to
  ``PlacementModel.reset_staging`` so the first post-recovery request
  re-establishes the wire base from a full restage, and the existing
  ``delta-base-mismatch`` machinery covers anything that slips through.

The flip counters/gauge land in metrics/components.py; ``last_mode``
("remote" | "local-fallback" | "local-degraded") is what the model
surfaces as ``last_solver``.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

import jax

from koordinator_tpu.metrics.components import (
    SOLVER_DEGRADED,
    SOLVER_FAILOVERS,
    SOLVER_LOCAL_SOLVES,
)
from koordinator_tpu.obs.device import DEVICE_OBS
from koordinator_tpu.obs.flight import FLIGHT
from koordinator_tpu.obs.trace import TRACER
from koordinator_tpu.ops.binpack import solve_batch
from koordinator_tpu.service.client import (
    SolverDeadlineExceeded,
    SolverOverloaded,
    SolverUnavailable,
)
from koordinator_tpu.service.supervisor import connection_probe

#: the in-process fallback solve — the exact program the sidecar runs
#: (service/server._jit_solve), compiled lazily on the first degraded
#: solve so the healthy path never pays for it. Nothing is donated: the
#: staged base is reused tick-to-tick by the staging cache.
_local_solve = DEVICE_OBS.jit("failover_local_solve", jax.jit(
    solve_batch, static_argnames=("config",), donate_argnums=()
))
# warm pool (docs/DESIGN.md §21): the local twin shares solve_batch's
# PROGRAM identity with the sidecar's binding, so signatures a running
# sidecar persisted warm THIS binding in the scheduler process — the
# first degraded-mode solve deserializes instead of cold-compiling.
# Adoption is donation-free by construction (§19.2).
from koordinator_tpu.service.warmpool import WARM_POOL  # noqa: E402

WARM_POOL.adopt(_local_solve, solve_batch, config_argpos=3)


class FailoverSolver:
    """A PlacementModel backend wrapping :class:`RemoteSolver` with
    degraded-mode failover (ISSUE: a sidecar outage must not skip
    rounds). Drop-in: same ``solve_result`` signature, same
    ``supports_staging_delta`` advertisement."""

    def __init__(self, remote,
                 failure_threshold: int = 3,
                 recovery_probes: int = 2,
                 probe_fn: Optional[Callable[[], bool]] = None,
                 probe_timeout_s: float = 0.5,
                 on_flip_back: Optional[Callable[[], None]] = None,
                 clock=time.monotonic,
                 prewarm: bool = True):
        self._remote = remote
        self.failure_threshold = failure_threshold
        self.recovery_probes = recovery_probes
        self._probe_fn = probe_fn or (
            lambda: connection_probe(remote.address, probe_timeout_s)
        )
        #: wired post-construction by the control plane (build_scheduler
        #: points it at PlacementModel.reset_staging); set-once wiring,
        #: read-only afterwards — deliberately outside the lock map
        self.on_flip_back = on_flip_back
        #: fired (outside the lock) right after the machine flips TO
        #: degraded. The pipelined tick loop wires both flip hooks to a
        #: pipeline drain so a mode transition never interleaves with an
        #: in-flight tick's publish (docs/DESIGN.md §15); set-once
        #: wiring like on_flip_back, deliberately outside the lock map
        self.on_flip_degraded: Optional[Callable[[], None]] = None
        self._clock = clock
        #: delta staging rides through to the remote solver; the local
        #: path solves the full staged state it is handed anyway
        self.supports_staging_delta = getattr(
            remote, "supports_staging_delta", False
        )
        self._lock = threading.Lock()
        self.degraded = False
        self.degraded_since: Optional[float] = None
        self.consecutive_failures = 0
        self.healthy_probes = 0
        self.flips_to_degraded = 0
        self.flips_to_remote = 0
        self.local_solves = 0
        self.last_error: Optional[str] = None
        #: which path answered the last solve: "remote" |
        #: "local-fallback" (remote tried and failed this solve) |
        #: "local-degraded" (machine flipped, remote not attempted)
        self.last_mode: Optional[str] = None
        #: the local twin's warm restore report (set by the background
        #: prewarm; set-once wiring like on_flip_back, read for status)
        self.prewarm_report: Optional[dict] = None
        if prewarm and self._warm_pool().active:
            # pre-compile/pre-load the local twin NOW, in the
            # background, so the first degraded-mode solve — the
            # moment the watchdog used to flag — is warm instead of
            # paying a multi-second cold compile (DESIGN §21)
            self.prewarm()

    @staticmethod
    def _warm_pool():
        """The pool the local twin is adopted into (tests re-adopt the
        binding into their own pools; production uses the singleton)."""
        return getattr(_local_solve, "_warm", None) or WARM_POOL

    def prewarm(self, background: bool = True) -> Optional[dict]:
        """Restore (or cold-compile, off-path) the local twin's hot
        signatures from the warm pool's manifest. Synchronous when
        ``background=False`` (tests)."""
        pool = self._warm_pool()
        if not background:
            report = pool.restore(
                fns=("failover_local_solve",), compile_missing=True,
            )
            self.prewarm_report = report
            return report

        def _go():
            self.prewarm_report = pool.restore(
                fns=("failover_local_solve",), compile_missing=True,
            )

        threading.Thread(target=_go, daemon=True,
                         name="failover-prewarm").start()
        return None

    # -- the backend call ----------------------------------------------------

    def solve_result(self, state, batch, params, config,
                     quota_state=None, gang_state=None, extras=None,
                     resv=None, numa=None, staging=None):
        with self._lock:
            degraded = self.degraded
        if degraded:
            if self.maybe_recover():
                return self._remote_solve(
                    state, batch, params, config, quota_state,
                    gang_state, extras, resv, numa, staging,
                )
            return self._local(
                state, batch, params, config, quota_state, gang_state,
                extras, resv, numa, mode="local-degraded",
            )
        return self._remote_solve(
            state, batch, params, config, quota_state, gang_state,
            extras, resv, numa, staging,
        )

    def _remote_solve(self, state, batch, params, config, quota_state,
                      gang_state, extras, resv, numa, staging):
        kwargs = {}
        if staging is not None and getattr(
            self._remote, "supports_staging_delta", False
        ):
            kwargs["staging"] = staging
        try:
            result = self._remote.solve_result(
                state, batch, params, config, quota_state, gang_state,
                extras, resv, numa, **kwargs,
            )
        except (SolverUnavailable, SolverDeadlineExceeded,
                SolverOverloaded) as e:
            # overloaded counts too: the sidecar is alive but SHEDDING
            # this caller past its retry budget — from the scheduler's
            # seat that is indistinguishable from an outage, and
            # letting it escape would crash the round loop outright
            flipped = False
            with self._lock:
                self.consecutive_failures += 1
                self.last_error = f"{type(e).__name__}: {e}"
                if (
                    not self.degraded
                    and self.consecutive_failures >= self.failure_threshold
                ):
                    self.degraded = True
                    self.degraded_since = self._clock()
                    self.healthy_probes = 0
                    self.flips_to_degraded += 1
                    flipped = True
            if flipped:
                SOLVER_FAILOVERS.inc({"direction": "to-degraded"})
                SOLVER_DEGRADED.set(1)
                TRACER.instant("failover-flip", cat="failover",
                               args={"direction": "to-degraded"})
                FLIGHT.trigger(
                    "failover-flip",
                    detail=f"to-degraded: {type(e).__name__}: {e}",
                )
                if self.on_flip_degraded is not None:
                    self.on_flip_degraded()
            return self._local(
                state, batch, params, config, quota_state, gang_state,
                extras, resv, numa, mode="local-fallback",
            )
        with self._lock:
            self.consecutive_failures = 0
            self.last_mode = "remote"
        return result

    def _local(self, state, batch, params, config, quota_state,
               gang_state, extras, resv, numa, mode: str):
        result = _local_solve(
            state, batch, params, config, quota_state, gang_state,
            extras, resv, numa,
        )
        with self._lock:
            self.local_solves += 1
            self.last_mode = mode
        SOLVER_LOCAL_SOLVES.inc({"mode": mode})
        return result

    # -- recovery ------------------------------------------------------------

    def maybe_recover(self) -> bool:
        """One hysteresis step: spend a probe on the sidecar; after
        ``recovery_probes`` consecutive healthy ones, flip back to
        remote (with the epoch reset). Called automatically by every
        degraded solve; idle loops may call it between ticks to recover
        without waiting for traffic. Returns True iff this call flipped
        the machine back."""
        with self._lock:
            if not self.degraded:
                return False
        ok = self._probe_fn()
        recovered = False
        with self._lock:
            if not self.degraded:
                return False
            if ok:
                self.healthy_probes += 1
                if self.healthy_probes >= self.recovery_probes:
                    self.degraded = False
                    self.degraded_since = None
                    self.healthy_probes = 0
                    self.consecutive_failures = 0
                    self.flips_to_remote += 1
                    recovered = True
            else:
                self.healthy_probes = 0
        if recovered:
            # the restarted sidecar holds no delta base: drop ours, and
            # let the model rebuild its staged world from scratch so the
            # re-established base starts from a full restage
            reset = getattr(self._remote, "reset_base", None)
            if reset is not None:
                reset()
            if self.on_flip_back is not None:
                self.on_flip_back()
            SOLVER_FAILOVERS.inc({"direction": "to-remote"})
            SOLVER_DEGRADED.set(0)
            TRACER.instant("failover-flip", cat="failover",
                           args={"direction": "to-remote"})
            FLIGHT.trigger("failover-flip", detail="to-remote: recovered")
        return recovered

    # -- plumbing ------------------------------------------------------------

    def close(self) -> None:
        close = getattr(self._remote, "close", None)
        if close is not None:
            close()

    def status(self) -> Dict[str, object]:
        with self._lock:
            return {
                "degraded": self.degraded,
                "degraded_for_s": (
                    None if self.degraded_since is None
                    else self._clock() - self.degraded_since
                ),
                "consecutive_failures": self.consecutive_failures,
                "failure_threshold": self.failure_threshold,
                "healthy_probes": self.healthy_probes,
                "recovery_probes": self.recovery_probes,
                "flips_to_degraded": self.flips_to_degraded,
                "flips_to_remote": self.flips_to_remote,
                "local_solves": self.local_solves,
                "last_mode": self.last_mode,
                "last_error": self.last_error,
                "prewarm": self.prewarm_report,
            }
