"""Gang / coscheduling: all-or-nothing group admission.

Device path: ops/gang.py (segment feasibility in the batched solver).
Host path: gang/manager.py (the incremental Permit-barrier state machine
with Strict/NonStrict modes and schedule-cycle bookkeeping).
"""

from koordinator_tpu.gang.manager import GangManager  # noqa: F401
