"""Host gang state machine: the incremental Permit-barrier path.

TPU-native rebuild of the reference's PodGroupManager + Gang/GangGroupInfo
(pkg/scheduler/plugins/coscheduling/core/{core,gang,ganggroup}.go;
SURVEY.md A.5). The batched solver resolves gangs with a segment
feasibility pass (ops/gang.py); this manager provides the same observable
semantics for pod-at-a-time scheduling: PreFilter gating (min-member,
schedule-cycle validity in Strict mode), the Permit wait barrier over
gang groups, and whole-group rejection on a Strict member's failure.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Dict, List, Optional, Sequence, Set, Tuple

from koordinator_tpu.apis.types import GangMode, GangSpec


class GangMatchPolicy(enum.Enum):
    """Which members count toward the Permit barrier (gang.go:496-510)."""

    ONCE_SATISFIED = "once-satisfied"      # default: sticky after first success
    ONLY_WAITING = "only-waiting"
    WAITING_AND_RUNNING = "waiting-and-running"


class PermitResult(enum.Enum):
    ALLOW = "allow"
    WAIT = "wait"
    NOT_GANG = "not-gang"


@dataclasses.dataclass
class _GroupInfo:
    """Shared per-gang-group scheduling-cycle state (ganggroup.go)."""

    gangs: Set[str]
    schedule_cycle: int = 1
    cycle_valid: bool = True
    child_cycle: Dict[str, int] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class _GangRecord:
    spec: GangSpec
    policy: GangMatchPolicy
    children: Set[str] = dataclasses.field(default_factory=set)
    waiting: Set[str] = dataclasses.field(default_factory=set)   # assumed
    bound: Set[str] = dataclasses.field(default_factory=set)
    once_satisfied: bool = False

    def valid_for_permit(self) -> bool:
        if self.policy == GangMatchPolicy.ONLY_WAITING:
            return len(self.waiting) >= self.spec.min_member
        if self.policy == GangMatchPolicy.WAITING_AND_RUNNING:
            return len(self.waiting) + len(self.bound) >= self.spec.min_member
        return (
            self.once_satisfied
            or len(self.waiting) + len(self.bound) >= self.spec.min_member
        )


class GangManager:
    """Registry + state machine over all gangs."""

    def __init__(self) -> None:
        self.gangs: Dict[str, _GangRecord] = {}
        self.groups: Dict[str, _GroupInfo] = {}
        self.gang_group_key: Dict[str, str] = {}  # gang name -> groups key
        self.pod_gang: Dict[str, str] = {}

    # -- registry -----------------------------------------------------------

    def update_gang(
        self, spec: GangSpec, policy: GangMatchPolicy = GangMatchPolicy.ONCE_SATISFIED
    ) -> None:
        existing = self.gangs.get(spec.name)
        record = _GangRecord(spec=spec, policy=policy)
        if existing is not None:
            record.children = existing.children
            record.waiting = existing.waiting
            record.bound = existing.bound
            record.once_satisfied = existing.once_satisfied
        self.gangs[spec.name] = record
        group_names = tuple(sorted(spec.gang_group)) or (spec.name,)
        key = "/".join(group_names)
        old_key = self.gang_group_key.get(spec.name)
        if old_key is not None and old_key != key:
            # gang moved to a different group: drop it from the stale one
            old_group = self.groups.get(old_key)
            if old_group is not None:
                old_group.gangs.discard(spec.name)
                if not old_group.gangs:
                    del self.groups[old_key]
        group = self.groups.setdefault(key, _GroupInfo(gangs=set(group_names)))
        group.gangs.update(group_names)
        for name in group_names:
            self.gang_group_key[name] = key

    def _group_of(self, gang_name: str) -> Optional[_GroupInfo]:
        key = self.gang_group_key.get(gang_name)
        return self.groups.get(key) if key is not None else None

    def on_pod_add(self, pod_uid: str, gang_name: str) -> None:
        record = self.gangs.get(gang_name)
        if record is not None:
            record.children.add(pod_uid)
            self.pod_gang[pod_uid] = gang_name

    def on_pod_delete(self, pod_uid: str) -> None:
        gang_name = self.pod_gang.pop(pod_uid, None)
        if gang_name is None:
            return
        record = self.gangs.get(gang_name)
        if record is not None:
            record.children.discard(pod_uid)
            record.waiting.discard(pod_uid)
            record.bound.discard(pod_uid)
        # drop the pod's schedule-cycle attempt record, otherwise stale
        # entries wedge (or prematurely reopen) the group's cycle
        group = self._group_of(gang_name)
        if group is not None:
            group.child_cycle.pop(pod_uid, None)

    # -- PreFilter (core.go:232-291) ---------------------------------------

    def pre_filter(self, pod_uid: str) -> Optional[str]:
        """None = pass; a string is the rejection reason."""
        gang_name = self.pod_gang.get(pod_uid)
        if gang_name is None:
            return None
        record = self.gangs.get(gang_name)
        if record is None:
            return f"gang {gang_name} not found"
        if record.policy == GangMatchPolicy.ONCE_SATISFIED and record.once_satisfied:
            return None
        if len(record.children) < record.spec.min_member:
            return (
                f"gang {gang_name} has not collected enough children: "
                f"{len(record.children)} < {record.spec.min_member}"
            )
        group = self._group_of(gang_name)
        if group is None:
            return None
        self._try_set_cycle_valid(group)
        gang_cycle = group.schedule_cycle
        try:
            if record.spec.mode == GangMode.STRICT:
                if not group.cycle_valid:
                    return f"gang {gang_name} schedule cycle invalid"
                if group.child_cycle.get(pod_uid, 0) >= gang_cycle:
                    return (
                        f"pod {pod_uid} schedule cycle too large "
                        f"({group.child_cycle.get(pod_uid, 0)} >= {gang_cycle})"
                    )
            return None
        finally:
            # mirrors the deferred setChildScheduleCycle (core.go:274)
            group.child_cycle[pod_uid] = gang_cycle

    def _try_set_cycle_valid(self, group: _GroupInfo) -> None:
        """ganggroup.go:101-124: once every child of the group has attempted
        the current cycle, open the next one."""
        total = sum(
            len(self.gangs[g].children) for g in group.gangs if g in self.gangs
        )
        attempted = sum(
            1 for c in group.child_cycle.values() if c == group.schedule_cycle
        )
        if attempted == total and total > 0:
            group.schedule_cycle += 1
            group.cycle_valid = True

    # -- Permit (core.go:358-385) ------------------------------------------

    def permit(self, pod_uid: str) -> Tuple[PermitResult, float]:
        gang_name = self.pod_gang.get(pod_uid)
        if gang_name is None:
            return PermitResult.NOT_GANG, 0.0
        record = self.gangs.get(gang_name)
        if record is None:
            return PermitResult.NOT_GANG, 0.0
        record.waiting.add(pod_uid)
        group = self._group_of(gang_name)
        members = group.gangs if group is not None else {gang_name}
        for name in members:
            other = self.gangs.get(name)
            if other is None or not other.valid_for_permit():
                return PermitResult.WAIT, record.spec.wait_time
        return PermitResult.ALLOW, 0.0

    def allow_gang_group(self, gang_name: str) -> List[str]:
        """Permit barrier opened: all waiting pods of the group are released
        for binding; gangs become once-satisfied."""
        group = self._group_of(gang_name)
        members = group.gangs if group is not None else {gang_name}
        released: List[str] = []
        for name in members:
            record = self.gangs.get(name)
            if record is None:
                continue
            record.once_satisfied = True
            for uid in sorted(record.waiting):
                released.append(uid)
                record.bound.add(uid)
            record.waiting.clear()
        return released

    # -- failure handling ---------------------------------------------------

    def unreserve(self, pod_uid: str) -> List[str]:
        """A member failed after Reserve (or timed out at Permit): Strict
        gangs reject the whole group (core.go:390-430). Returns the uids
        whose assumed resources must be released."""
        gang_name = self.pod_gang.get(pod_uid)
        if gang_name is None:
            return []
        record = self.gangs.get(gang_name)
        if record is None:
            return []
        record.waiting.discard(pod_uid)
        if (
            record.policy == GangMatchPolicy.ONCE_SATISFIED
            and record.once_satisfied
        ) or record.spec.mode != GangMode.STRICT:
            return []
        return self.reject_gang_group(gang_name)

    def reject_gang_group(self, gang_name: str) -> List[str]:
        """Reject every waiting pod of the group and invalidate its cycle."""
        group = self._group_of(gang_name)
        members = group.gangs if group is not None else {gang_name}
        rejected: List[str] = []
        for name in members:
            record = self.gangs.get(name)
            if record is None:
                continue
            rejected.extend(sorted(record.waiting))
            record.waiting.clear()
        if group is not None:
            group.cycle_valid = False
        return rejected

    def on_pod_waiting(self, pod_uid: str) -> None:
        """A batched-path pod entered the Permit barrier (the incremental
        path records this inside :meth:`permit`)."""
        gang_name = self.pod_gang.get(pod_uid)
        record = self.gangs.get(gang_name) if gang_name else None
        if record is not None:
            record.waiting.add(pod_uid)

    def on_pod_forgotten(self, pod_uid: str) -> None:
        """An assumed pod was forgotten before its bind published (a
        deposed leader's aborted round, an auditor repair): drop it from
        waiting/bound without deregistering it from the gang — the pod
        itself returns to pending and will re-attempt. ``once_satisfied``
        deliberately stays sticky (the reference's semantics)."""
        gang_name = self.pod_gang.get(pod_uid)
        record = self.gangs.get(gang_name) if gang_name else None
        if record is not None:
            record.waiting.discard(pod_uid)
            record.bound.discard(pod_uid)

    def on_pod_bound(self, pod_uid: str) -> None:
        gang_name = self.pod_gang.get(pod_uid)
        record = self.gangs.get(gang_name) if gang_name else None
        if record is None:
            return
        record.waiting.discard(pod_uid)
        record.bound.add(pod_uid)
        if len(record.bound) >= record.spec.min_member:
            record.once_satisfied = True
