"""Feature gates: three registries toggling optional subsystems.

Reference: pkg/features/ — manager/webhook gates (features.go:28-90),
scheduler gates (scheduler_features.go:32-59), koordlet gates
(koordlet_features.go:33-143 with defaults :154-173). Gates parse the
k8s-style ``--feature-gates=Name=true,Other=false`` spec and components
consult them at construction time.
"""

from __future__ import annotations

from typing import Dict, Iterable, Mapping, Optional


class FeatureGate:
    """A mutable gate registry (componentbase featuregate.FeatureGate)."""

    def __init__(self, defaults: Mapping[str, bool]):
        self._defaults: Dict[str, bool] = dict(defaults)
        self._overrides: Dict[str, bool] = {}

    def known(self) -> Iterable[str]:
        return sorted(self._defaults)

    def enabled(self, feature: str) -> bool:
        if feature not in self._defaults:
            raise KeyError(f"unknown feature gate {feature!r}")
        return self._overrides.get(feature, self._defaults[feature])

    def set(self, feature: str, value: bool) -> None:
        if feature not in self._defaults:
            raise KeyError(f"unknown feature gate {feature!r}")
        self._overrides[feature] = bool(value)

    def set_from_spec(self, spec: str) -> None:
        """Parse "A=true,B=false" (the --feature-gates flag format).
        Atomic: an invalid spec leaves the registry untouched."""
        if not spec:
            return
        parsed: Dict[str, bool] = {}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            if "=" not in part:
                raise ValueError(f"invalid feature gate spec {part!r}")
            name, raw = part.split("=", 1)
            name = name.strip()
            raw = raw.strip().lower()
            if raw not in ("true", "false"):
                raise ValueError(f"invalid feature gate value {part!r}")
            if name not in self._defaults:
                raise KeyError(f"unknown feature gate {name!r}")
            parsed[name] = raw == "true"
        self._overrides.update(parsed)

    def copy(self) -> "FeatureGate":
        """A fresh gate with this registry's effective values as defaults
        (builders copy the module registry so per-build --feature-gates
        overrides never leak across builds)."""
        return FeatureGate(self.as_dict())

    def as_dict(self) -> Dict[str, bool]:
        return {name: self.enabled(name) for name in self._defaults}


#: koordlet gates (koordlet_features.go:154-173 defaults)
KOORDLET_GATES = FeatureGate({
    "AuditEvents": False,
    "AuditEventsHTTPHandler": False,
    "BECPUSuppress": True,
    "BECPUManager": False,
    "BECPUEvict": False,
    "BEMemoryEvict": False,
    "CPUBurst": True,
    "SystemConfig": False,
    "RdtResctrl": True,
    "CgroupReconcile": False,
    "NodeTopologyReport": True,
    "Accelerators": False,
    "CPICollector": False,
    "Libpfm4": False,
    "PSICollector": False,
    "BlkIOReconcile": False,
    "ColdPageCollector": False,
    "HugePageReport": False,
})

#: manager/webhook gates (features.go:28-90)
MANAGER_GATES = FeatureGate({
    "PodMutatingWebhook": True,
    "PodValidatingWebhook": True,
    "ElasticMutatingWebhook": True,
    "ElasticValidatingWebhook": True,
    "NodeMutatingWebhook": False,
    "NodeValidatingWebhook": False,
    "ConfigMapValidatingWebhook": False,
    "ColocationProfileSkipMutatingResources": False,
    "WebhookFramework": True,
    "MultiQuotaTree": False,
    "ElasticQuotaIgnorePodOverhead": False,
    "ElasticQuotaGuaranteeUsage": False,
    "DisableDefaultQuota": False,
    "SupportParentQuotaSubmitPod": False,
    "DisablePVCReservation": False,
})

#: scheduler gates (scheduler_features.go:32-59)
SCHEDULER_GATES = FeatureGate({
    "CompatibleCSIStorageCapacity": False,
    "DisableCSIStorageCapacityInformer": False,
    "CompatiblePodDisruptionBudget": False,
    "DisablePodDisruptionBudgetInformer": False,
    "ResizePod": False,
    #: TPU-native gates: the batched device solver vs incremental-only
    "BatchedPlacement": True,
    "ElasticQuotaPreemption": True,
})
